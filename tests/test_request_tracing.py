"""Request-lifecycle tracing + step-phase attribution
(telemetry/spans.py, serve/slo.py, and their data-plane wiring):
SpanBuffer ring/export semantics, StepProfiler's exclusive accounting
and the phase-sum ≈ step-wall invariant on a real batcher, SLO
burn-rate windows, and the fleet simulator's full-chain Perfetto
export (LB select → queue → admission → prefill → decode → delivery
as one correlated trace row per request)."""
import json

import jax.numpy as jnp
import pytest

from skypilot_tpu.metrics import REGISTRY
from skypilot_tpu.serve import slo as slo_lib
from skypilot_tpu.telemetry import spans as spans_lib
from skypilot_tpu.telemetry import steplog
from skypilot_tpu.telemetry import trace as trace_lib


# --- SpanBuffer ring semantics ----------------------------------------------

def test_span_buffer_ring_drops_oldest_and_counts():
    buf = spans_lib.SpanBuffer(capacity=3, clock=lambda: 0.0)
    for i in range(5):
        buf.record(f's{i}', float(i), float(i) + 0.5)
    assert len(buf) == 3
    assert buf.dropped == 2
    assert [s['name'] for s in buf.snapshot()] == ['s2', 's3', 's4']
    buf.clear()
    assert len(buf) == 0
    with pytest.raises(ValueError):
        spans_lib.SpanBuffer(capacity=0)


def test_span_context_manager_uses_buffer_clock():
    ticks = iter([10.0, 12.5])
    buf = spans_lib.SpanBuffer(clock=lambda: next(ticks))
    with buf.span('work', trace_id='t1', request_id=7, mode='cold'):
        pass
    (span,) = buf.snapshot()
    assert span == {'name': 'work', 't0': 10.0, 't1': 12.5,
                    'trace_id': 't1', 'request_id': 7,
                    'attrs': {'mode': 'cold'}}


def test_events_are_chrome_trace_complete_events():
    buf = spans_lib.SpanBuffer(pid=3, tid=1, clock=lambda: 0.0)
    buf.record('a', 1.0, 1.5, trace_id='t', request_id=2, tokens=4)
    buf.record('b', 2.0, 2.0)                    # instant marker
    ev_a, ev_b = buf.events()
    assert ev_a['ph'] == 'X' and ev_a['cat'] == 'skypilot_tpu_span'
    assert ev_a['ts'] == 1.0e6 and ev_a['dur'] == pytest.approx(0.5e6)
    assert ev_a['pid'] == 3 and ev_a['tid'] == 1
    assert ev_a['args'] == {'trace_id': 't', 'request_id': 2,
                            'tokens': 4}
    assert ev_b['dur'] == 0.0 and 'args' not in ev_b


# --- export: merge, sort, byte determinism ----------------------------------

def test_export_merges_into_existing_trace_file(tmp_path):
    path = str(tmp_path / 'trace.json')
    a = spans_lib.SpanBuffer(pid=1, clock=lambda: 0.0)
    a.record('first', 0.0, 1.0)
    assert a.export(path) == 1
    b = spans_lib.SpanBuffer(pid=2, clock=lambda: 0.0)
    b.record('second', 2.0, 3.0)
    assert b.export(path, extra_events=[
        {'name': 'extra', 'ts': 4e6, 'dur': 0.0, 'pid': 9, 'tid': 0}]) == 2
    with open(path, encoding='utf-8') as f:
        names = [e['name'] for e in json.load(f)['traceEvents']]
    # The second export appended under the file lock — never clobbered.
    assert names == ['first', 'second', 'extra']


def test_export_sorted_and_byte_deterministic(tmp_path):
    def build():
        buf = spans_lib.SpanBuffer(pid=0, tid=0, clock=lambda: 0.0)
        buf.record('late', 5.0, 6.0)
        buf.record('early', 1.0, 2.0, trace_id='t')
        return buf
    p1, p2 = str(tmp_path / 'a.json'), str(tmp_path / 'b.json')
    build().export(p1)
    build().export(p2)
    raw1 = open(p1, 'rb').read()
    assert raw1 == open(p2, 'rb').read()
    events = json.loads(raw1)['traceEvents']
    assert [e['name'] for e in events] == ['early', 'late']


# --- module-level gating ----------------------------------------------------

def test_module_record_gated_by_set_enabled(monkeypatch):
    monkeypatch.delenv(spans_lib.ENV_VAR, raising=False)
    monkeypatch.delenv(spans_lib.TIMELINE_ENV_VAR, raising=False)
    default = spans_lib.default_buffer()
    default.clear()
    try:
        assert not spans_lib.enabled()
        spans_lib.record('off', 0.0, 1.0)
        with spans_lib.span('off_ctx'):
            pass
        assert len(default) == 0                 # cheap no-op when off
        spans_lib.set_enabled(True)
        assert spans_lib.enabled()
        spans_lib.record('on', 0.0, 1.0)
        assert [s['name'] for s in default.snapshot()] == ['on']
        spans_lib.set_enabled(False)             # forced off beats env
        monkeypatch.setenv(spans_lib.ENV_VAR, '1')
        assert not spans_lib.enabled()
        spans_lib.set_enabled(None)              # None restores env gating
        assert spans_lib.enabled()
    finally:
        spans_lib.set_enabled(None)
        default.clear()


# --- StepProfiler exclusive accounting --------------------------------------

def test_step_profiler_nested_phase_pauses_enclosing():
    ticks = iter([0.0,    # start
                  1.0,    # enter decode
                  3.0,    # enter host_fetch (decode charged [1, 3))
                  7.0,    # exit host_fetch (host_fetch charged [3, 7))
                  9.0,    # exit decode (decode charged [7, 9))
                  10.0])  # finish
    prof = spans_lib.StepProfiler(clock=lambda: next(ticks))
    prof.start()
    with prof.phase('decode'):
        with prof.phase('host_fetch'):
            pass
    phases = prof.finish()
    assert phases == {'decode': 4.0, 'host_fetch': 4.0}
    assert prof.last_wall == 10.0
    # Exclusive by construction: phase sum never exceeds wall.
    assert sum(phases.values()) <= prof.last_wall


def test_step_profiler_inert_outside_a_step():
    prof = spans_lib.StepProfiler(clock=lambda: 0.0)
    with prof.phase('decode'):                   # no start(): stays inert
        pass
    assert prof.finish() == {}
    assert prof.last_phases == {} and prof.last_wall == 0.0


# --- SLO burn rates ---------------------------------------------------------

def test_slo_config_validation():
    with pytest.raises(ValueError):
        slo_lib.SLOConfig(objective=1.0)
    with pytest.raises(ValueError):
        slo_lib.SLOConfig(fast_window_s=100.0, slow_window_s=10.0)


def test_slo_burn_rate_math_and_eviction():
    cfg = slo_lib.SLOConfig(ttft_target_s=1.0, objective=0.9,
                            fast_window_s=10.0, slow_window_s=100.0)
    mon = slo_lib.SLOMonitor(cfg)
    assert mon.burn_rates(now=0.0) == {'fast': 0.0, 'slow': 0.0}
    for t, ttft in ((0.0, 0.5), (1.0, 2.0), (2.0, 0.5), (3.0, 2.0)):
        mon.observe_ttft(ttft, now=t)
    # 2 of 4 violating against a 10% budget: burn = 0.5 / 0.1 = 5.
    rates = mon.burn_rates(now=3.0)
    assert rates == {'fast': pytest.approx(5.0),
                     'slow': pytest.approx(5.0)}
    # 50s later the fast window has evicted everything; slow remembers.
    rates = mon.burn_rates(now=53.0)
    assert rates['fast'] == 0.0
    assert rates['slow'] == pytest.approx(5.0)
    assert mon.samples_total == 4 and mon.violations_total == 2


def test_slo_tpot_disabled_when_target_none():
    mon = slo_lib.SLOMonitor(slo_lib.SLOConfig(ttft_target_s=1.0,
                                               tpot_target_s=None))
    mon.observe_tpot(99.0, now=0.0)
    assert mon.samples_total == 0
    mon = slo_lib.SLOMonitor(slo_lib.SLOConfig(ttft_target_s=None,
                                               tpot_target_s=0.1))
    mon.observe_ttft(99.0, now=0.0)              # TTFT disabled too
    mon.observe_tpot(0.2, now=0.0)
    assert mon.samples_total == 1 and mon.violations_total == 1


def test_slo_export_sets_burn_gauge():
    mon = slo_lib.SLOMonitor(slo_lib.SLOConfig(ttft_target_s=1.0,
                                               objective=0.99))
    mon.observe_ttft(5.0, now=0.0)
    rates = mon.export(now=0.0)
    assert rates['fast'] == pytest.approx(100.0)
    assert REGISTRY.get_sample_value(
        'skytpu_serve_slo_burn_rate',
        {'window': 'fast'}) == pytest.approx(100.0)


# --- batcher wiring: spans + phase-sum invariant (tiny jax model) -----------

from skypilot_tpu.models import llama  # noqa: E402

_CFG = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, d_ff=128,
                         max_seq_len=128, dtype=jnp.float32)


@pytest.fixture(scope='module')
def tiny_params():
    import jax
    return llama.init_params(_CFG, jax.random.PRNGKey(0))


def _batcher(params, **kw):
    from skypilot_tpu.infer.engine import GeneratorConfig
    from skypilot_tpu.infer.serving import ContinuousBatcher
    span_buffer = kw.pop('span_buffer', None)
    return ContinuousBatcher(
        params, _CFG,
        GeneratorConfig(max_seq_len=128, batch_size=2, temperature=0.0,
                        prompt_buckets=[16, 32]),
        decode_chunk=4, span_buffer=span_buffer)


def test_batcher_emits_request_spans_with_trace_id(tiny_params):
    buf = spans_lib.SpanBuffer()
    b = _batcher(tiny_params, span_buffer=buf)
    with trace_lib.trace_scope('feedbeef'):
        rid = b.submit([5, 6, 7], max_new_tokens=8)
    b.run_until_idle()
    assert b.result(rid)
    names = {s['name'] for s in buf.snapshot()}
    assert {'queue_wait', 'admit', 'prefill_chunk', 'decode_chunk',
            'delivery'} <= names
    # Per-request spans carry the propagated trace id; batch-level
    # decode chunks stay untagged.
    by_name = {}
    for s in buf.snapshot():
        by_name.setdefault(s['name'], []).append(s)
    for name in ('queue_wait', 'admit', 'delivery'):
        assert all(s.get('trace_id') == 'feedbeef'
                   and s.get('request_id') == rid
                   for s in by_name[name]), name
    assert all('trace_id' not in s for s in by_name['decode_chunk'])
    # Spans are well-formed intervals.
    assert all(s['t1'] >= s['t0'] for s in buf.snapshot())


def test_step_phase_sum_within_10pct_of_wall(tiny_params):
    """The acceptance invariant: EXCLUSIVE phase accounting means the
    per-step phase sum covers the step wall up to un-phased scheduler
    bookkeeping, asserted < 10% in aggregate over a real run."""
    b = _batcher(tiny_params)
    b.submit([1, 2, 3, 4], max_new_tokens=10)
    b.submit([9, 8, 7], max_new_tokens=10)
    total_phases = total_wall = 0.0
    steps = 0
    while b.num_active or b.num_queued:
        b.step()
        phases = b._profiler.last_phases
        wall = b._profiler.last_wall
        assert set(phases) <= set(spans_lib.STEP_PHASES)
        assert sum(phases.values()) <= wall * (1 + 1e-6)
        total_phases += sum(phases.values())
        total_wall += wall
        steps += 1
    assert steps > 0 and total_wall > 0
    assert total_phases >= 0.9 * total_wall
    # The metrics export saw the same attribution.
    decode_count = REGISTRY.get_sample_value(
        'skytpu_infer_step_phase_seconds_count', {'phase': 'decode'})
    assert decode_count and decode_count > 0
    util = REGISTRY.get_sample_value(
        'skytpu_infer_step_utilization', {'phase': 'decode'})
    assert util is not None and 0.0 <= util <= 1.0


def test_step_phases_written_to_steplog(tiny_params, tmp_path,
                                        monkeypatch):
    path = str(tmp_path / 'steps.jsonl')
    monkeypatch.setenv(steplog.ENV_VAR, path)
    b = _batcher(tiny_params)
    b.submit([4, 5], max_new_tokens=4)
    b.run_until_idle()
    records = [r for r in steplog.read(path)
               if r.get('kind') == 'infer_step_phases']
    assert records
    rec = records[-1]
    assert rec['wall_s'] > 0
    assert set(rec['phases']) <= set(spans_lib.STEP_PHASES)


# --- fleet simulator: full-chain export -------------------------------------

def test_simulator_exports_full_request_chains(tmp_path):
    from skypilot_tpu.serve.traffic import generator as gen
    from skypilot_tpu.serve.traffic.simulator import (FleetSimulator,
                                                      SimConfig)
    sim = FleetSimulator(
        SimConfig(policy='least_load', num_replicas=2, batch_size=2,
                  decode_chunk=4, slo_ttft_s=1.5, slo_tpot_s=0.5,
                  prefill_cost_per_token_s=4e-3, prefix_cache_mb=0.25),
        gen.TrafficConfig(seed=5, duration_s=5.0, base_rps=5.0,
                          num_sessions=4, num_heads=2, head_tokens=32,
                          session_share=0.8))
    summary = sim.run()
    # SLO burn rates ride along in the summary (virtual clock).
    assert summary['slo_burn_fast'] >= 0.0
    assert summary['slo_burn_slow'] >= 0.0
    path = str(tmp_path / 'serve_trace.json')
    exported = sim.export_trace(path)
    assert exported == sim.span_count() > 0
    with open(path, encoding='utf-8') as f:
        events = json.load(f)['traceEvents']
    assert len(events) == exported
    chains = {}
    for e in events:
        tid = (e.get('args') or {}).get('trace_id')
        if tid:
            chains.setdefault(tid, set()).add(e['name'])
    # At least one request renders as the full LB → delivery chain.
    required = {'lb.select', 'queue_wait', 'admit', 'delivery'}
    full = [tid for tid, names in chains.items()
            if required <= names
            and names & {'prefill_chunk', 'fused_tick'}]
    assert full
    # Sim-plane events use the fixed pid 0; replicas use rid + 1.
    pids = {e['pid'] for e in events}
    assert 0 in pids and pids <= {0, 1, 2}
