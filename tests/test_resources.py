import pytest

from skypilot_tpu import Resources
from skypilot_tpu import exceptions


def test_default():
    r = Resources()
    assert r.cloud is None
    assert r.accelerators is None
    assert not r.is_launchable


def test_tpu_accelerator_string():
    r = Resources(accelerators='tpu-v5e-16')
    assert r.accelerators == {'tpu-v5e-16': 1}
    assert r.tpu_spec.num_hosts == 4
    assert r.runtime_version == 'v2-alpha-tpuv5-lite'


def test_accelerator_alias_and_dict():
    r = Resources(accelerators={'v5litepod-8': 1})
    assert r.accelerator_name == 'tpu-v5e-8'


def test_infra_parsing():
    r = Resources(infra='gcp/us-central2/us-central2-b')
    assert r.cloud == 'gcp'
    assert r.region == 'us-central2'
    assert r.zone == 'us-central2-b'
    r2 = Resources(infra='gcp/*/us-east5-a')
    assert r2.region is None and r2.zone == 'us-east5-a'


def test_cpus_plus_notation():
    r = Resources(cpus='4+', memory=16)
    assert r.cpus == '4+'
    assert r.memory == '16'
    with pytest.raises(exceptions.InvalidTaskError):
        Resources(cpus='abc')


def test_yaml_roundtrip():
    r = Resources(infra='gcp/us-central2', accelerators='tpu-v5e-16:1',
                  use_spot=True, disk_size=100,
                  accelerator_args={'runtime_version': 'v2-alpha-tpuv5-lite'})
    cfg = r.to_yaml_config()
    r2 = Resources.from_dict(cfg)
    assert r == r2
    assert r2.use_spot and r2.disk_size == 100


def test_any_of_candidates():
    candidates = Resources.from_yaml_config({
        'accelerators': 'tpu-v5e-8',
        'any_of': [{'use_spot': True}, {'use_spot': False}],
    })
    assert len(candidates) == 2
    assert candidates[0].use_spot and not candidates[1].use_spot
    assert all(c.accelerator_name == 'tpu-v5e-8' for c in candidates)


def test_multislice_args():
    r = Resources(accelerators='tpu-v5e-256',
                  accelerator_args={'num_slices': 4})
    assert r.num_slices == 4


def test_copy_override():
    r = Resources(accelerators='tpu-v4-8')
    r2 = r.copy(region='us-central2', cloud='gcp')
    assert r2.region == 'us-central2'
    assert r2.accelerator_name == 'tpu-v4-8'
    assert r.region is None  # immutability


def test_job_recovery():
    r = Resources(job_recovery='FAILOVER')
    assert r.job_recovery == {'strategy': 'failover',
                              'max_restarts_on_errors': 0}
