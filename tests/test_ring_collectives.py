"""Ring collective primitives (parallel/collectives.py) against the
monolithic-collective oracles, on the hermetic 8-device CPU mesh.

The contract under test is the one the overlapped decode path leans
on: `ring_all_gather` moves the same BITS as `lax.all_gather` (rank
order, no arithmetic), `ring_reduce_scatter` matches `psum_scatter`'s
tiled contract, and `pipelined_psum` accumulates in flat mesh-rank
order on every shard REGARDLESS of chunk count — that fixed order is
what makes greedy decode bit-stable across chunk policies.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel import collectives
from skypilot_tpu.parallel.collectives import shard_map

N = 4


def _mesh1():
    return Mesh(np.array(jax.devices()[:N]), ('x',))


def _mesh2():
    return Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ('tp', 'tpq'))


def _run(mesh, f, x, in_specs, out_specs):
    # check_vma off: the ring primitives build replicated values out of
    # ppermutes + axis_index math the replication checker can't see
    # through (same setting the overlapped decode region uses).
    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))(x)


def test_ring_perm_is_forward_neighbor_ring():
    assert collectives._ring_perm(4) == [(0, 1), (1, 2), (2, 3), (3, 0)]
    assert collectives._ring_perm(1) == [(0, 0)]


def test_chunk_bounds_array_split_convention():
    assert collectives.chunk_bounds(8, 2) == [(0, 4), (4, 8)]
    # Non-divisible: first dim % chunks spans are one longer.
    assert collectives.chunk_bounds(7, 3) == [(0, 3), (3, 5), (5, 7)]
    # chunks > dim clamps to dim; chunks <= 1 is one span.
    assert collectives.chunk_bounds(3, 10) == [(0, 1), (1, 2), (2, 3)]
    assert collectives.chunk_bounds(5, 0) == [(0, 5)]
    for dim, chunks in ((13, 4), (1, 1), (64, 3)):
        bounds = collectives.chunk_bounds(dim, chunks)
        assert bounds[0][0] == 0 and bounds[-1][1] == dim
        assert all(a[1] == b[0] for a, b in zip(bounds, bounds[1:]))


@pytest.mark.parametrize('tiled', [False, True])
def test_ring_all_gather_bitwise_matches_all_gather(tiled):
    mesh = _mesh1()
    x = jax.random.normal(jax.random.PRNGKey(0), (N * 3, 5), jnp.float32)
    out_specs = P(*([None] * (2 if tiled else 3)))
    ring = _run(mesh,
                lambda a: collectives.ring_all_gather(a, 'x', tiled=tiled),
                x, P('x', None), out_specs)
    oracle = _run(mesh,
                  lambda a: jax.lax.all_gather(a, 'x', tiled=tiled),
                  x, P('x', None), out_specs)
    # Pure data movement: identical bits, not just identical values.
    assert np.array_equal(np.asarray(ring), np.asarray(oracle))


def test_ring_all_gather_single_rank_identity():
    mesh = Mesh(np.array(jax.devices()[:1]), ('x',))
    x = jnp.arange(6, dtype=jnp.float32).reshape(3, 2)
    out = _run(mesh, lambda a: collectives.ring_all_gather(a, 'x'),
               x, P('x', None), P(None, None, None))
    assert np.array_equal(np.asarray(out), np.asarray(x)[None])


def test_ring_reduce_scatter_matches_psum_scatter():
    mesh = _mesh1()
    c = 2
    x = jax.random.normal(jax.random.PRNGKey(1), (N * N * c, 3),
                          jnp.float32)
    ring = _run(mesh, lambda a: collectives.ring_reduce_scatter(a, 'x'),
                x, P('x', None), P('x', None))
    oracle = _run(mesh,
                  lambda a: jax.lax.psum_scatter(a, 'x', tiled=True),
                  x, P('x', None), P('x', None))
    np.testing.assert_allclose(np.asarray(ring), np.asarray(oracle),
                               rtol=1e-6)
    # Integer payload: associativity is exact, so so is the match.
    xi = jnp.arange(N * N * c * 3, dtype=jnp.int32).reshape(N * N * c, 3)
    ring_i = _run(mesh, lambda a: collectives.ring_reduce_scatter(a, 'x'),
                  xi, P('x', None), P('x', None))
    oracle_i = _run(mesh,
                    lambda a: jax.lax.psum_scatter(a, 'x', tiled=True),
                    xi, P('x', None), P('x', None))
    assert np.array_equal(np.asarray(ring_i), np.asarray(oracle_i))


def test_ring_reduce_scatter_rejects_non_divisible():
    mesh = _mesh1()
    x = jnp.zeros((N * 5, 3), jnp.float32)   # per-shard leading dim 5
    with pytest.raises(ValueError, match='not.*divisible|divisible'):
        _run(mesh, lambda a: collectives.ring_reduce_scatter(a, 'x'),
             x, P('x', None), P('x', None))


@pytest.mark.parametrize('chunks', [1, 2, 3, 8, 64])
def test_pipelined_psum_matches_psum(chunks):
    mesh = _mesh1()
    x = jax.random.normal(jax.random.PRNGKey(2), (N, 2, 24), jnp.float32)

    def ring(a):
        red, _ = collectives.pipelined_psum(a, 'x', chunks=chunks)
        return red

    out = _run(mesh, ring, x, P('x', None, None), P('x', None, None))
    oracle = _run(mesh, lambda a: jax.lax.psum(a, 'x'),
                  x, P('x', None, None), P('x', None, None))
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=1e-6)


def test_pipelined_psum_rank_order_is_chunk_invariant():
    # The determinism contract: any chunked schedule accumulates in
    # flat mesh-rank order, so results are BIT-identical across chunk
    # counts and equal to a sequential rank-0-first numpy sum.
    mesh = _mesh1()
    x = jax.random.normal(jax.random.PRNGKey(3), (N, 33), jnp.float32)

    def run(chunks):
        def f(a):
            red, _ = collectives.pipelined_psum(a, 'x', chunks=chunks)
            return red
        return np.asarray(_run(mesh, f, x, P('x', None), P('x', None)))

    ref = np.asarray(x)[0]
    for r in range(1, N):
        ref = ref + np.asarray(x)[r]      # rank order, f32 throughout
    for c in (2, 3, 4):
        out = run(c)
        assert np.array_equal(out, np.tile(ref, (N, 1))), \
            f'chunks={c} diverged from rank-order accumulation'


def test_pipelined_psum_multi_axis_rank_order():
    # ('tp', 'tpq') flattens major-to-minor: (0,0), (0,1), (1,0), (1,1).
    mesh = _mesh2()
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 2, 20), jnp.float32)

    def f(a):
        red, _ = collectives.pipelined_psum(a, ('tp', 'tpq'), chunks=2)
        return red

    out = np.asarray(_run(mesh, f, x, P('tp', 'tpq', None),
                          P('tp', 'tpq', None)))
    xs = np.asarray(x)
    ref = xs[0, 0]
    for i, j in ((0, 1), (1, 0), (1, 1)):
        ref = ref + xs[i, j]
    for i in range(2):
        for j in range(2):
            assert np.array_equal(out[i, j], ref)


def test_pipelined_psum_on_chunk_bounds_and_results():
    mesh = _mesh1()
    d = 10
    x = jnp.ones((N, d), jnp.float32)
    seen = []

    def f(a):
        def on_chunk(ci, lo, span):
            seen.append((ci, lo, span.shape[-1]))
            return span * 0 + ci
        red, results = collectives.pipelined_psum(a, 'x', chunks=3,
                                                  on_chunk=on_chunk)
        return red, jnp.concatenate(results, axis=-1)

    red, tagged = _run(mesh, f, x, P('x', None),
                       (P('x', None), P('x', None)))
    # array_split convention over d=10: spans 4, 3, 3.
    assert seen == [(0, 0, 4), (1, 4, 3), (2, 7, 3)]
    assert np.array_equal(np.asarray(red), np.full((N, d), float(N)))
    expect = np.concatenate([np.full((4,), 0.0), np.full((3,), 1.0),
                             np.full((3,), 2.0)])
    assert np.array_equal(np.asarray(tagged),
                          np.tile(expect, (N, 1)).astype(np.float32))


def test_pipelined_psum_chunks_one_invokes_on_chunk_once():
    mesh = _mesh1()
    x = jnp.ones((N, 6), jnp.float32)
    seen = []

    def f(a):
        def on_chunk(ci, lo, span):
            seen.append((ci, lo, span.shape[-1]))
            return span
        red, results = collectives.pipelined_psum(a, 'x', chunks=1,
                                                  on_chunk=on_chunk)
        return red, results[0]

    red, only = _run(mesh, f, x, P('x', None), (P('x', None), P('x', None)))
    assert seen == [(0, 0, 6)]           # whole reduced vector, once
    assert np.array_equal(np.asarray(red), np.asarray(only))


def test_shard_map_shim_accepts_modern_kwargs():
    # The jax<0.5 shim must accept the modern call surface (check_vma=)
    # — every shard_map in the repo routes through it.
    mesh = _mesh1()
    x = jnp.arange(N, dtype=jnp.float32)
    out = jax.jit(shard_map(lambda a: jax.lax.psum(a, 'x'), mesh=mesh,
                            in_specs=P('x'), out_specs=P('x'),
                            check_vma=False))(x)
    assert np.array_equal(np.asarray(out), np.full((N,), 6.0))


def test_shard_map_shim_mesh_none_needs_modern_jax():
    if hasattr(jax, 'shard_map'):
        pytest.skip('jax >= 0.5: mesh-free shard_map is native')
    with pytest.raises(NotImplementedError):
        shard_map(lambda a: a, in_specs=P('x'), out_specs=P('x'))
