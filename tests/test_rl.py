"""GRPO RL post-training (train/rl.py — the verl-recipe analog):
advantage math, loss masking/gradients, and the end-to-end property
that matters — the policy measurably moves toward the reward."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import MeshConfig, make_mesh
from skypilot_tpu.parallel import sharding as sharding_lib
from skypilot_tpu.train import rl

CFG = llama.LlamaConfig(vocab_size=64, d_model=64, n_layers=2,
                        n_heads=4, n_kv_heads=2, d_ff=128,
                        max_seq_len=128, dtype=jnp.float32, remat=False)


def test_group_advantages_standardizes_within_groups():
    rewards = np.array([1.0, 3.0, 10.0, 10.0])
    adv = rl.group_advantages(rewards, group_size=2)
    np.testing.assert_allclose(adv[:2], [-1.0, 1.0], atol=1e-4)
    # Degenerate group (all equal): zero advantage, no div-by-zero.
    np.testing.assert_allclose(adv[2:], [0.0, 0.0], atol=1e-4)
    with pytest.raises(ValueError):
        rl.group_advantages(np.ones(5), group_size=2)


def test_build_batch_masks_only_completion():
    batch = rl.build_batch([[5, 6]], [[7, 8, 9]], [1.0], pad_to=8)
    assert batch['tokens'][0].tolist() == [5, 6, 7, 8, 9, 0, 0, 0]
    # mask[t] gates the prediction of tokens[t+1]: positions predicting
    # 7, 8, 9 (indices 1, 2, 3) are on; prompt + padding off.
    assert batch['completion_mask'][0].tolist() == \
        [0, 1, 1, 1, 0, 0, 0]


def test_grpo_loss_gradient_direction():
    """Positive-advantage completions must get MORE likely after a
    gradient step; negative-advantage ones less likely."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    batch = {k: jnp.asarray(v) for k, v in rl.build_batch(
        [[1, 2], [1, 2]], [[3, 4], [5, 6]], [1.0, -1.0],
        pad_to=8).items()}

    def lp_of(params, row):
        lp = rl._token_logprobs(params, batch['tokens'][row:row + 1],
                                CFG)
        mask = batch['completion_mask'][row:row + 1]
        return float((lp * mask).sum())

    grads = jax.grad(rl.grpo_loss)(params, batch, config=CFG)
    stepped = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
    assert lp_of(stepped, 0) > lp_of(params, 0)   # reinforced
    assert lp_of(stepped, 1) < lp_of(params, 1)   # suppressed


def test_kl_penalty_pulls_toward_reference():
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    ref = llama.init_params(CFG, jax.random.PRNGKey(1))
    batch = {k: jnp.asarray(v) for k, v in rl.build_batch(
        [[1]], [[3, 4, 5]], [0.0], pad_to=8).items()}
    # Zero advantage isolates the KL term; the penalty must be positive
    # for a policy that differs from the reference and ~0 at the
    # reference itself.
    loss_diff = rl.grpo_loss(params, batch, config=CFG, kl_coef=1.0,
                             ref_params=ref)
    loss_same = rl.grpo_loss(params, batch, config=CFG, kl_coef=1.0,
                             ref_params=params)
    assert float(loss_diff) > float(loss_same)
    assert abs(float(loss_same)) < 1e-5


@pytest.mark.slow
def test_grpo_learns_target_token_reward():
    """The e2e property: a few GRPO iterations measurably raise the
    reward (policy emits the target token more often)."""
    target = 7

    def reward(prompt, completion):
        return sum(1 for t in completion if t == target) / max(
            len(completion), 1)

    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(dp=jax.device_count()))
    trainer = rl.GrpoTrainer(params, CFG, mesh,
                             sharding_lib.LLAMA_RULES, reward,
                             group_size=8, max_new_tokens=8,
                             temperature=1.0, learning_rate=5e-3,
                             total_steps=12, seed=3)
    prompts = [[11, 13], [17, 19]]
    history = [trainer.step(prompts)['reward_mean'] for _ in range(10)]
    early = float(np.mean(history[:3]))
    late = float(np.mean(history[-3:]))
    assert late > early + 0.1, f'no learning: {history}'
