"""Async SDK + version handshake (reference: sky/client/sdk_async.py,
sky/server/versions.py)."""
import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from skypilot_tpu.server import server as server_lib
from skypilot_tpu.server import versions


def test_version_constants_sane():
    assert versions.MIN_COMPATIBLE_API_VERSION <= versions.API_VERSION


def test_client_compat_checks():
    ok, _ = versions.check_client_compatible(None)
    assert ok  # legacy clients tolerated
    ok, _ = versions.check_client_compatible(str(versions.API_VERSION))
    assert ok
    ok, msg = versions.check_client_compatible('0')
    assert not ok and 'Upgrade the client' in msg
    ok, msg = versions.check_client_compatible('garbage')
    assert not ok


def test_server_compat_checks():
    ok, _ = versions.check_server_compatible(str(versions.API_VERSION))
    assert ok
    ok, msg = versions.check_server_compatible('0')
    assert not ok and 'server' in msg.lower()


def test_server_stamps_headers_and_rejects_old_clients(tmp_home):
    async def _run():
        c = TestClient(TestServer(server_lib.make_app()))
        await c.start_server()
        try:
            r = await c.get('/api/health')
            assert r.headers[versions.API_VERSION_HEADER] == \
                str(versions.API_VERSION)
            assert versions.VERSION_HEADER in r.headers
            # Incompatibly old client -> 400 with upgrade hint.
            r = await c.get('/api/health',
                            headers={versions.API_VERSION_HEADER: '0'})
            assert r.status == 400
            body = await r.json()
            assert 'Upgrade the client' in body['error']
        finally:
            await c.close()

    asyncio.new_event_loop().run_until_complete(_run())


def test_async_sdk_local_mode(tmp_home):
    """Async SDK drives a full launch→status→queue→down cycle in
    library-local mode (no server configured)."""
    import skypilot_tpu as sky
    from skypilot_tpu.client import sdk_async

    async def _run():
        task = sky.Task(run='echo async-ok', name='t')
        task.set_resources(sky.Resources(cloud='local'))
        await sdk_async.launch(task, cluster_name='async-c')
        try:
            rows = await sdk_async.status()
            assert rows[0]['name'] == 'async-c'
            jobs = await sdk_async.queue('async-c', all_jobs=True)
            assert jobs and jobs[0]['status'] == 'SUCCEEDED'
            report = await sdk_async.cost_report()
            assert any(r['name'] == 'async-c' for r in report)
        finally:
            await sdk_async.down('async-c')
        rows = await sdk_async.status()
        assert not rows

    asyncio.new_event_loop().run_until_complete(_run())


def test_async_rest_client_against_server(tmp_home):
    """AsyncRestClient handshake + submit/get against a live app."""
    from skypilot_tpu.client.sdk_async import AsyncRestClient

    async def _run():
        c = TestClient(TestServer(server_lib.make_app()))
        await c.start_server()
        try:
            url = str(c.make_url(''))
            client = AsyncRestClient(url)
            result = await client.submit_and_get('/status', {})
            assert result == []
            assert client._version_checked
        finally:
            await c.close()

    asyncio.new_event_loop().run_until_complete(_run())
