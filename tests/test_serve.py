"""Serve: spec parsing, autoscalers, LB policies, spot placer, and an
end-to-end service on the hermetic local cloud (analog of the reference's
tests/test_jobs_and_serve.py + smoke test_sky_serve.py)."""
import time

import pytest
import requests

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import autoscalers as asc
from skypilot_tpu.serve import load_balancing_policies as lbp
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import spot_placer as spl
from skypilot_tpu.serve.controller import ServeController
from skypilot_tpu.serve.load_balancer import SkyServeLoadBalancer
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from tests.test_launch_e2e import iso_state  # noqa: F401  (fixture reuse)


# --- spec ---

pytestmark = pytest.mark.slow


def test_spec_parse_roundtrip():
    spec = ServiceSpec.from_yaml_config({
        'readiness_probe': {'path': '/health',
                            'initial_delay_seconds': 30},
        'replica_policy': {'min_replicas': 1, 'max_replicas': 3,
                           'target_qps_per_replica': 10},
        'ports': 9000,
    })
    assert spec.autoscaling_enabled
    spec2 = ServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert spec == spec2


def test_spec_shorthand_and_validation():
    spec = ServiceSpec.from_yaml_config({'replicas': 2,
                                         'readiness_probe': '/'})
    assert spec.min_replicas == 2 and not spec.autoscaling_enabled
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(readiness_path='no-slash')
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(min_replicas=3, max_replicas=1)
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(target_qps_per_replica=1.0)  # needs max_replicas
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(min_replicas=1, max_replicas=2)  # needs target_qps
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(load_balancing_policy='nope')


# --- autoscalers ---

def _fake_replicas(n_ready, n_other=0, status=ReplicaStatus.STARTING,
                   is_spot=False):
    out = []
    for i in range(n_ready):
        out.append({'replica_id': i + 1, 'status': ReplicaStatus.READY,
                    'launched_at': time.time(), 'is_spot': is_spot})
    for i in range(n_other):
        out.append({'replica_id': n_ready + i + 1, 'status': status,
                    'launched_at': time.time(), 'is_spot': is_spot})
    return out


def _rate_spec(**kw):
    base = dict(min_replicas=1, max_replicas=4, target_qps_per_replica=1.0,
                upscale_delay_seconds=40, downscale_delay_seconds=40)
    base.update(kw)
    return ServiceSpec(**base)


def test_fixed_autoscaler_holds_target():
    a = asc.Autoscaler.from_spec('svc', ServiceSpec(min_replicas=2))
    assert isinstance(a, asc.FixedSizeAutoscaler)
    ups = a.generate_scaling_decisions([])
    assert len(ups) == 2
    assert all(d.operator == asc.AutoscalerDecisionOperator.SCALE_UP
               for d in ups)
    assert a.generate_scaling_decisions(_fake_replicas(2)) == []
    downs = a.generate_scaling_decisions(_fake_replicas(3))
    assert [d.operator for d in downs] == \
        [asc.AutoscalerDecisionOperator.SCALE_DOWN]


def test_request_rate_autoscaler_hysteresis():
    a = asc.RequestRateAutoscaler('svc', _rate_spec())
    # threshold = 40s / 20s interval = 2 consecutive over-target passes.
    assert a.scale_up_threshold == 2
    now = time.time()
    # ~3 qps sustained for LONGER than the QPS window, so the
    # cold-start clamp (denominator = min(window, elapsed)) uses the
    # full window: 177 in-window samples / 60 s = 2.95 qps.
    a.collect_request_information(
        {'timestamps': [now - i * 0.34 for i in range(180)]})
    a.generate_scaling_decisions(_fake_replicas(1))
    assert a.target_num_replicas == 1  # one pass: not yet
    decisions = a.generate_scaling_decisions(_fake_replicas(1))
    assert a.target_num_replicas == 3  # ceil(3 qps / 1 qps-per-replica)
    assert len(decisions) == 2
    # Idle long enough -> downscale after 2 passes.
    a.request_timestamps.clear()
    a.generate_scaling_decisions(_fake_replicas(3))
    decisions = a.generate_scaling_decisions(_fake_replicas(3))
    assert a.target_num_replicas == 1
    assert len(decisions) == 2


def test_autoscaler_scale_down_prefers_least_useful():
    replicas = [
        {'replica_id': 1, 'status': ReplicaStatus.READY,
         'launched_at': 1.0, 'is_spot': False},
        {'replica_id': 2, 'status': ReplicaStatus.PROVISIONING,
         'launched_at': 2.0, 'is_spot': False},
        {'replica_id': 3, 'status': ReplicaStatus.NOT_READY,
         'launched_at': 3.0, 'is_spot': False},
    ]
    victims = asc.select_replicas_to_scale_down(replicas, 2)
    assert victims == [2, 3]  # provisioning first, then not-ready


def test_fallback_autoscaler_spot_with_ondemand_base():
    spec = ServiceSpec(min_replicas=3, base_ondemand_fallback_replicas=1,
                       spot_placer='dynamic_fallback')
    a = asc.Autoscaler.from_spec('svc', spec)
    assert isinstance(a, asc.FallbackRequestRateAutoscaler)
    decisions = a.generate_scaling_decisions([])
    spot_ups = [d for d in decisions if d.target.get('use_spot')]
    od_ups = [d for d in decisions if d.target.get('use_spot') is False]
    assert len(spot_ups) == 2 and len(od_ups) == 1


def test_fallback_autoscaler_dynamic_cover():
    spec = ServiceSpec(min_replicas=2, dynamic_ondemand_fallback=True)
    a = asc.Autoscaler.from_spec('svc', spec)
    # No spot ready yet -> 2 spot + 2 dynamic on-demand cover.
    decisions = a.generate_scaling_decisions([])
    assert sum(1 for d in decisions if d.target.get('use_spot')) == 2
    assert sum(1 for d in decisions if not d.target.get('use_spot')) == 2
    # Both spot READY -> the on-demand cover is drained.
    replicas = _fake_replicas(2, is_spot=True) + \
        _fake_replicas(2, is_spot=False)
    decisions = a.generate_scaling_decisions(replicas)
    assert all(d.operator == asc.AutoscalerDecisionOperator.SCALE_DOWN
               for d in decisions)
    assert len(decisions) == 2


# --- LB policies ---

def test_round_robin_policy_cycles():
    p = lbp.LoadBalancingPolicy.make('round_robin')
    p.set_ready_replicas(['a', 'b', 'c'])
    picks = [p.select_replica() for _ in range(6)]
    assert sorted(picks[:3]) == ['a', 'b', 'c']
    assert picks[:3] == picks[3:]


def test_least_load_policy_tracks_inflight():
    p = lbp.LoadBalancingPolicy.make()  # default = least_load
    assert isinstance(p, lbp.LeastLoadPolicy)
    p.set_ready_replicas(['a', 'b'])
    first = p.select_replica()
    p.pre_execute_hook(first)
    assert p.select_replica() != first
    p.post_execute_hook(first)


# --- spot placer ---

def test_dynamic_fallback_spot_placer():
    locs = [spl.Location('gcp', 'us-central1', f'us-central1-{z}')
            for z in 'abc']
    placer = spl.DynamicFallbackSpotPlacer(locs)
    first = placer.select_next_location([])
    placer.set_preempted(first)
    nxt = placer.select_next_location([])
    assert nxt != first
    # All preempted -> hedge resets and still returns something.
    for loc in locs:
        placer.set_preempted(loc)
    assert placer.select_next_location([]) in locs


def test_spot_placer_balances_across_locations():
    locs = [spl.Location('gcp', 'us-central1', 'a'),
            spl.Location('gcp', 'us-central1', 'b')]
    placer = spl.DynamicFallbackSpotPlacer(locs)
    current = [locs[0]]
    assert placer.select_next_location(current) == locs[1]


# --- end-to-end on the local cloud ---

SERVICE_RUN = ('python3 -c "'
               "import http.server,os;"
               "http.server.HTTPServer(('127.0.0.1',"
               "int(os.environ['SKYPILOT_SERVE_PORT'])),"
               'http.server.SimpleHTTPRequestHandler).serve_forever()"')


def _service_task(min_replicas=1, port=8123):
    return task_lib.Task.from_yaml_config({
        'name': 'e2e-svc',
        'run': SERVICE_RUN,
        'resources': {'cloud': 'local'},
        'service': {
            'readiness_probe': {'path': '/', 'initial_delay_seconds': 60},
            'replica_policy': {'min_replicas': min_replicas},
            'ports': port,
        },
    })


@pytest.fixture()
def service(iso_state):  # noqa: F811
    from skypilot_tpu.serve import core as serve_core
    task = _service_task()
    serve_state.add_service('e2e-svc',
                            ServiceSpec.from_yaml_config(
                                task.service).to_yaml_config(),
                            task.to_yaml_config())
    controller = ServeController('e2e-svc', probe_interval=0.5)
    yield controller
    controller.stop()
    controller.manager.terminate_all()
    serve_core  # keep import


def _wait_ready(controller, n=1, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        controller.step()
        if len(controller.manager.ready_urls()) >= n:
            return True
        time.sleep(0.5)
    return False


def test_service_end_to_end(service):
    controller = service
    assert _wait_ready(controller), \
        serve_state.get_replicas('e2e-svc')
    record = serve_state.get_service('e2e-svc')
    assert record['status'] == ServiceStatus.READY
    # Load balancer proxies to the ready replica.
    lb = SkyServeLoadBalancer(controller, port=18931, sync_interval=60)
    lb.start()
    lb.sync_once()
    try:
        resp = requests.get('http://127.0.0.1:18931/', timeout=10)
        assert resp.status_code == 200
    finally:
        lb.stop()


def test_service_replica_failure_recovery(service, monkeypatch):
    controller = service
    assert _wait_ready(controller)
    # Kill the replica out from under the service (preemption analog).
    from skypilot_tpu.provision.local import instance as local_instance
    from skypilot_tpu.serve import replica_managers as rm
    monkeypatch.setattr(rm, 'PROBE_FAILURE_THRESHOLD', 1)
    [rec] = [r for r in serve_state.get_replicas('e2e-svc')
             if r['status'] == ReplicaStatus.READY]
    local_instance.simulate_preemption(rec['cluster_name'])
    deadline = time.time() + 120
    recovered = False
    while time.time() < deadline:
        controller.step()
        fresh = [r for r in serve_state.get_replicas('e2e-svc')
                 if r['status'] == ReplicaStatus.READY
                 and r['replica_id'] != rec['replica_id']]
        if fresh:
            recovered = True
            break
        time.sleep(0.5)
    assert recovered, serve_state.get_replicas('e2e-svc')
