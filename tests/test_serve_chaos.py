"""Serve-plane chaos tolerance (serve/failover.py +
serve/traffic/simulator.py chaos mode): circuit-breaking detection,
exactly-once session failover, preemption-notice handoff, and the
autoscaler treating dead replicas as capacity to replace.

All simulator tests run in VIRTUAL time on the seeded trace — no
sleeps, no wall-clock dependence — and the chaos runs must reproduce
the fault-free run's session outputs bit for bit (greedy decode).
Expensive fleet runs share one module-scoped fixture.
"""
import jax.numpy as jnp
import pytest

from skypilot_tpu.infer import block_pool as block_pool_lib
from skypilot_tpu.serve import autoscalers as asc
from skypilot_tpu.serve import failover as failover_lib
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.serve.traffic import generator as gen
from skypilot_tpu.serve.traffic.simulator import (ChaosConfig,
                                                  FaultEvent,
                                                  FleetSimulator,
                                                  SimConfig)
from tests.chaos import serve_faults


# --- fault plans ------------------------------------------------------------

def test_fault_event_validation():
    with pytest.raises(ValueError):
        FaultEvent(t=1.0, kind='explode', replica=0)
    with pytest.raises(ValueError):
        FaultEvent(t=1.0, kind='stall', replica=0)   # needs duration
    with pytest.raises(ValueError):
        ChaosConfig(failure_threshold=0)
    FaultEvent(t=1.0, kind='partition', replica=0, duration_s=2.0)


def test_draw_fault_plan_seeded_and_distinct():
    a = serve_faults.draw_fault_plan(7, 20.0, 4, n_faults=3)
    b = serve_faults.draw_fault_plan(7, 20.0, 4, n_faults=3)
    assert a == b
    assert a != serve_faults.draw_fault_plan(8, 20.0, 4, n_faults=3)
    assert len({e.replica for e in a}) == 3          # no double-kills
    assert all(0.15 * 20.0 <= e.t <= 0.70 * 20.0 for e in a)
    assert all(e.t <= n.t for e, n in zip(a, a[1:]))
    with pytest.raises(ValueError):
        serve_faults.draw_fault_plan(1, 20.0, 2, n_faults=3)
    with pytest.raises(ValueError):
        serve_faults.draw_fault_plan(1, 20.0, 4, kinds=['nope'])


# --- circuit breaker --------------------------------------------------------

def test_breaker_opens_after_consecutive_failures():
    cb = failover_lib.CircuitBreaker(failure_threshold=3)
    assert cb.note_failure('r0', now=0.0) is False
    assert cb.note_failure('r0', now=1.0) is False
    # A success in between resets the consecutive count.
    cb.note_success('r0')
    assert cb.note_failure('r0', now=2.0) is False
    assert cb.note_failure('r0', now=3.0) is False
    assert cb.note_failure('r0', now=4.0) is True    # threshold: opens
    assert cb.is_open('r0')
    assert cb.opens_total == 1
    assert cb.routable(['r0', 'r1'], now=4.0) == ['r1']


def test_breaker_half_open_probe_backoff_and_heal():
    cb = failover_lib.CircuitBreaker(failure_threshold=1)
    cb.note_failure('r0', now=0.0)
    assert cb.is_open('r0')
    # Probe gated on the backoff schedule (initial 0.5s, jitter 0).
    assert not cb.probe_due('r0', now=0.4)
    assert cb.probe_due('r0', now=0.5)
    # Failed probe: stays open, delay grows (0.5 -> 1.0).
    assert cb.note_failure('r0', now=0.5) is False
    assert not cb.probe_due('r0', now=1.4)
    assert cb.probe_due('r0', now=1.5)
    # Successful probe closes the circuit and reports the heal.
    assert cb.note_success('r0') is True
    assert not cb.is_open('r0')
    assert cb.routable(['r0'], now=1.6) == ['r0']


def test_breaker_backpressure_cools_down_without_counting_failure():
    cb = failover_lib.CircuitBreaker(failure_threshold=1)
    cb.note_backpressure('r0', now=0.0, retry_after_s=2.0)
    # Cooled down, NOT failed: excluded now, back after the advice,
    # and the circuit never opened.
    assert cb.routable(['r0'], now=1.0) == []
    assert cb.routable(['r0'], now=2.0) == ['r0']
    assert not cb.is_open('r0')
    assert cb.opens_total == 0


def test_breaker_forget_and_observe_members():
    cb = failover_lib.CircuitBreaker(failure_threshold=1)
    cb.note_failure('r0', now=0.0)
    cb.forget('r0')
    assert not cb.is_open('r0')          # state left with the replica
    cb.note_failure('r1', now=0.0)
    cb.observe_members(['r2'])
    assert cb.snapshot() == {}


# --- session journal --------------------------------------------------------

def test_journal_exactly_once_replay_spec():
    j = failover_lib.SessionJournal()
    j.open('s', prompt=[1, 2, 3], max_new_tokens=10, replica='r0')
    j.commit('s', [7, 8])
    j.commit('s', [9])
    spec = j.replay_spec('s')
    # Resume at the first un-delivered token: prompt+committed as the
    # new prompt, the un-delivered remainder as the new budget.
    assert spec['prompt'] == [1, 2, 3, 7, 8, 9]
    assert spec['max_new_tokens'] == 7
    j.reassign('s', 'r1')
    assert j.record('s').replica == 'r1'
    assert j.record('s').failovers == 1
    assert j.sessions_on('r0') == []
    assert j.sessions_on('r1') == ['s']
    # Budget exhausted -> nothing to replay (only the completion event
    # was lost).
    j.commit('s', [0] * 7)
    assert j.replay_spec('s') is None
    j.close('s')
    assert j.sessions_on('r1') == []
    with pytest.raises(ValueError):
        j.commit('s', [1])
    with pytest.raises(ValueError):
        j.open('s', [1], 1, 'r0')


# --- autoscaler: dead replicas are capacity to replace ----------------------

def test_alive_capacity_excludes_terminal_and_draining():
    replicas = [
        {'replica_id': 1, 'status': ReplicaStatus.READY,
         'launched_at': 1.0, 'is_spot': False},
        {'replica_id': 2, 'status': ReplicaStatus.FAILED,
         'launched_at': 2.0, 'is_spot': False},
        {'replica_id': 3, 'status': ReplicaStatus.READY,
         'launched_at': 3.0, 'is_spot': False, 'draining': True},
    ]
    alive = asc.alive_capacity(replicas)
    assert [r['replica_id'] for r in alive] == [1]
    # A fixed-size fleet of 3 with one dead and one draining must
    # launch 2 replacements, not absorb the load on the survivor.
    a = asc.Autoscaler.from_spec('svc', ServiceSpec(min_replicas=3))
    ups = a.generate_scaling_decisions(replicas)
    assert len(ups) == 2
    assert all(d.operator is asc.AutoscalerDecisionOperator.SCALE_UP
               for d in ups)


# --- batcher failover hooks (tiny jax model) --------------------------------

from skypilot_tpu.models import llama  # noqa: E402

_CFG = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, d_ff=128,
                         max_seq_len=128, dtype=jnp.float32)


@pytest.fixture(scope='module')
def tiny_params():
    import jax
    return llama.init_params(_CFG, jax.random.PRNGKey(0))


def _batcher(params, max_queue=None, decode_chunk=2, **kw):
    from skypilot_tpu.infer.engine import GeneratorConfig
    from skypilot_tpu.infer.serving import ContinuousBatcher
    base = dict(max_seq_len=128, batch_size=2, temperature=0.0,
                prompt_buckets=[16, 32])
    base.update(kw)
    return ContinuousBatcher(params, _CFG, GeneratorConfig(**base),
                             decode_chunk=decode_chunk,
                             max_queue=max_queue)


def test_export_cancel_replay_bit_exact(tiny_params):
    """The failover primitive: export mid-decode, cancel (blocks all
    released), replay prompt+out elsewhere -> bit-exact vs unfaulted."""
    ref_b = _batcher(tiny_params)
    ref_rid = ref_b.submit([5, 6, 7], max_new_tokens=12)
    ref_b.run_until_idle()
    ref = ref_b.result(ref_rid)

    victim = _batcher(tiny_params)
    rid = victim.submit([5, 6, 7], max_new_tokens=12)
    for _ in range(3):
        victim.step()
    spec = victim.export_session(rid)
    assert not spec['done'] and 0 < len(spec['out']) < 12
    got = victim.cancel(rid)
    assert got == spec['out']
    if victim.pooled:
        victim.pool.check_invariant()    # fencing released every block
    assert victim.num_active == 0 and victim.num_queued == 0

    survivor = _batcher(tiny_params)
    new_rid = survivor.submit(
        spec['prompt'] + spec['out'],
        max_new_tokens=spec['max_new_tokens'] - len(spec['out']))
    survivor.run_until_idle()
    assert spec['out'] + survivor.result(new_rid) == ref


def test_drain_sessions_hands_off_cleanly(tiny_params):
    b = _batcher(tiny_params)
    r1 = b.submit([3, 4, 5], max_new_tokens=8)
    r2 = b.submit([9, 10], max_new_tokens=8)
    b.step()
    specs = b.drain_sessions()
    assert [s['rid'] for s in specs] == [r1, r2]
    assert b.num_active == 0 and b.num_queued == 0
    if b.pooled:
        b.pool.check_invariant()


def test_max_queue_backpressure_raises_retryable(tiny_params):
    b = _batcher(tiny_params, batch_size=1, max_queue=1)
    b.submit([1, 2], max_new_tokens=4)   # fills the admission queue
    with pytest.raises(block_pool_lib.PoolExhaustedError) as ei:
        b.submit([5, 6], max_new_tokens=4)
    # Retryable: carries Retry-After advice for the 503 mapping.
    assert ei.value.retry_after_s is not None
    assert ei.value.retry_after_s >= 1.0
    b.run_until_idle()


# --- chaos fleet runs (module-shared, virtual time) -------------------------

_TRAFFIC = dict(seed=11, duration_s=10.0, base_rps=8.0, num_sessions=8,
                num_heads=6, head_tokens=64, session_share=0.85)
_SIM = dict(num_replicas=3, batch_size=2, decode_chunk=4, slo_ttft_s=1.5,
            prefill_cost_per_token_s=4e-3, prefix_cache_mb=0.25)

_KILL_PREEMPT = [FaultEvent(t=3.5, kind='kill', replica=0),
                 FaultEvent(t=5.5, kind='preempt', replica=1)]
_STALL_PARTITION = [
    FaultEvent(t=2.0, kind='stall', replica=0, duration_s=5.0),
    FaultEvent(t=3.0, kind='partition', replica=1, duration_s=4.0)]


def _run(policy, events=None):
    chaos = None
    if events is not None:
        chaos = ChaosConfig(events=list(events))
    sim = FleetSimulator(SimConfig(policy=policy, **_SIM),
                         gen.TrafficConfig(**_TRAFFIC), chaos)
    summary = sim.run()
    return sim, summary


@pytest.fixture(scope='module')
def chaos_runs():
    """Five runs on ONE contended trace: fault-free baselines for both
    policies, kill+preempt twice (determinism), stall+partition once."""
    base_sim, base = _run('least_load')
    kp_sim, kp = _run('least_load', _KILL_PREEMPT)
    kp2_sim, kp2 = _run('least_load', _KILL_PREEMPT)
    pa_sim, _ = _run('prefix_affinity')
    sp_sim, sp = _run('prefix_affinity', _STALL_PARTITION)
    return {
        'base': base, 'base_outputs': base_sim.session_outputs(),
        'kp': kp, 'kp_outputs': kp_sim.session_outputs(), 'kp2': kp2,
        'kp_sim': kp_sim, 'kp2_sim': kp2_sim,
        'pa_outputs': pa_sim.session_outputs(),
        'sp': sp, 'sp_outputs': sp_sim.session_outputs(),
        'sp_sim': sp_sim,
    }


def test_chaos_inert_when_config_absent(chaos_runs):
    # The no-chaos path must not even report a chaos section — the
    # parity contract with pre-chaos summaries.
    assert 'chaos' not in chaos_runs['base']


def test_kill_preempt_all_sessions_complete_bit_exact(chaos_runs):
    base, kp = chaos_runs['base'], chaos_runs['kp']
    # 100% of sessions completed despite losing 2 of 3 replicas...
    assert kp['requests'] == base['requests'] > 0
    assert kp['chaos']['sessions_lost'] == 0
    # ...with zero lost/duplicated tokens: greedy replay is bit-exact
    # against the fault-free run, session by session.
    assert chaos_runs['kp_outputs'] == chaos_runs['base_outputs']
    assert kp['chaos']['sessions_recovered'] > 0     # kill -> replayed
    assert kp['chaos']['sessions_handed_off'] > 0    # preempt -> drained
    assert kp['chaos']['circuit_opens'] == 1         # only the kill


def test_kill_preempt_failover_metrics_reported(chaos_runs):
    c = chaos_runs['kp']['chaos']
    assert c['failover_p99_ms'] is not None
    assert c['failover_p99_ms'] >= c['failover_p50_ms'] > 0
    assert c['replayed_tokens'] >= 0
    # BlockPool.check_invariant ran on every survivor at each fence.
    assert c['invariant_checks'] > 0
    kinds = [e['kind'] for e in c['faults'] if 'kind' in e]
    assert kinds == ['kill', 'preempt']
    assert any(e.get('event') == 'circuit_open' for e in c['faults'])


def test_kill_removes_replica_preempt_drains(chaos_runs):
    sim = chaos_runs['kp_sim']
    assert [r.replica_id for r in sim.dead] == [0]       # killed
    urls = {r.url for r in sim.replicas}
    assert 'replica-0' not in urls
    assert 'replica-1' not in urls                       # drained out
    assert any(r.replica_id == 1 for r in sim.retired)


def test_chaos_summary_deterministic(chaos_runs):
    assert chaos_runs['kp'] == chaos_runs['kp2']


def test_stall_partition_heal_and_bit_exact(chaos_runs):
    sp = chaos_runs['sp']
    # Transient faults: delayed delivery is fine, lost/duplicated is
    # not — outputs still match the fault-free prefix_affinity run.
    assert chaos_runs['sp_outputs'] == chaos_runs['pa_outputs']
    assert sp['chaos']['sessions_lost'] == 0
    # Both replicas healed and rejoined the ring.
    heals = [e for e in sp['chaos']['faults']
             if e.get('event') == 'heal']
    assert len(heals) == 2
    urls = {r.url for r in chaos_runs['sp_sim'].replicas}
    assert {'replica-0', 'replica-1'} <= urls


def test_failover_leaves_span_breadcrumb_trail(chaos_runs, tmp_path):
    """A killed replica's interrupted sessions must be reconstructable
    from the exported timeline: failover.detect -> failover.replay ->
    failover.resume in time order on the victim session's trace row."""
    import json
    sim = chaos_runs['kp_sim']
    path = tmp_path / 'chaos_trace.json'
    exported = sim.export_trace(str(path))
    assert exported == sim.span_count() > 0
    with open(path, encoding='utf-8') as f:
        events = json.load(f)['traceEvents']
    per_trace = {}
    for e in events:
        tid = (e.get('args') or {}).get('trace_id')
        if tid:
            per_trace.setdefault(tid, []).append(e)
    chain = ('failover.detect', 'failover.replay', 'failover.resume')
    full_chains = 0
    for tid, evs in per_trace.items():
        names = {e['name'] for e in evs}
        if 'failover.resume' not in names:
            continue
        # A resumed session always shows the whole breadcrumb trail...
        assert set(chain) <= names, (tid, sorted(names))
        # ...in causal order.
        first_ts = {n: min(e['ts'] for e in evs if e['name'] == n)
                    for n in chain}
        assert (first_ts['failover.detect']
                <= first_ts['failover.replay']
                <= first_ts['failover.resume']), (tid, first_ts)
        # The replay re-prefills prompt + committed on the survivor.
        replay = next(e for e in evs
                      if e['name'] == 'failover.replay')
        assert replay['args']['replayed'] >= 0
        full_chains += 1
    assert full_chains > 0
    assert full_chains >= chaos_runs['kp']['chaos']['sessions_recovered']


def test_chaos_trace_export_byte_deterministic(chaos_runs, tmp_path):
    """Virtual clocks + fixed pids: two runs of the same seeded chaos
    scenario export byte-identical Perfetto files to fresh paths."""
    a, b = tmp_path / 'a.json', tmp_path / 'b.json'
    chaos_runs['kp_sim'].export_trace(str(a))
    chaos_runs['kp2_sim'].export_trace(str(b))
    raw = a.read_bytes()
    assert raw and raw == b.read_bytes()


def test_autoscaler_replaces_killed_replica(monkeypatch):
    # A fixed-size fleet of 2 loses one replica mid-trace: the dead
    # replica reports FAILED (terminal) and the autoscaler launches a
    # replacement instead of absorbing its load on the survivor.
    # Decision cadence tightened so a decision lands inside the short
    # virtual trace (still deterministic: virtual time, not wall).
    monkeypatch.setattr(asc, 'DECISION_INTERVAL_SECONDS', 2)
    traffic = gen.TrafficConfig(seed=3, duration_s=8.0, base_rps=3.0,
                                num_sessions=4, num_heads=2)
    sim = FleetSimulator(
        SimConfig(policy='least_load', num_replicas=2, batch_size=2,
                  decode_chunk=4, prefix_cache_mb=None),
        traffic,
        ChaosConfig(events=[FaultEvent(t=2.0, kind='kill', replica=0)]))
    autoscaler = asc.Autoscaler.from_spec(
        'sim', ServiceSpec(min_replicas=2))
    summary = sim.run(autoscaler=autoscaler)
    assert [r.replica_id for r in sim.dead] == [0]
    assert summary['replicas'] == 2          # replacement launched
    assert summary['chaos']['sessions_lost'] == 0
    assert any(r.replica_id >= 2 for r in sim.replicas)
