"""Serve e2e: one replica SPANNING MULTIPLE HOSTS of its slice.

The service's replica resources ask for a 2-host TPU slice
(local-cloud emulation: tpu-v5e-8 = 2 host processes); the replica task
runs the real serving script on every host under the gang env contract.
The hosts join one jax.distributed process group, decode is sharded over
the global ('tp',) mesh (infer/multihost.py), only the head binds HTTP,
and the replica manager probes/serves through the head — proving a model
bigger than one host's HBM can serve.  Reference capability:
llm/vllm/service.yaml tensor-parallel replicas +
sky/backends/cloud_vm_ray_backend.py:6306 pod-host semantics.
"""
import os
import time

import pytest
import requests

from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.controller import ServeController
from skypilot_tpu.serve.service_spec import ServiceSpec

from tests.test_launch_e2e import iso_state  # noqa: F401  (fixture reuse)

pytestmark = pytest.mark.slow

_SCRIPT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', 'examples', 'scripts',
                 'serve_llama.py'))

# XLA_FLAGS cleared: the pytest conftest's forced-host-device-count leaks
# into spawned ranks and would override --devices-per-host.
_RUN = ('export XLA_FLAGS=; export JAX_PLATFORMS=cpu; '
        f'python {_SCRIPT} --port $SKYPILOT_SERVE_PORT '
        '--model-size tiny-tp --max-seq-len 128 --batch-size 2 '
        '--devices-per-host 2')


def _service_task():
    return task_lib.Task.from_yaml_config({
        'name': 'mh-svc',
        'run': _RUN,
        # tpu-v5e-8 on the local cloud = 2 emulated hosts x 4 chips;
        # the serving script itself uses 2 virtual CPU devices per host.
        'resources': {'cloud': 'local', 'accelerators': 'tpu-v5e-8'},
        'service': {
            'readiness_probe': {'path': '/health',
                                'initial_delay_seconds': 300},
            'replica_policy': {'min_replicas': 1},
            'ports': 18473,
        },
    })


@pytest.fixture()
def mh_service(iso_state):  # noqa: F811
    task = _service_task()
    serve_state.add_service('mh-svc',
                            ServiceSpec.from_yaml_config(
                                task.service).to_yaml_config(),
                            task.to_yaml_config())
    controller = ServeController('mh-svc', probe_interval=1.0)
    yield controller
    controller.stop()
    controller.manager.terminate_all()


def test_multihost_replica_serves(mh_service):
    controller = mh_service
    deadline = time.time() + 300
    while time.time() < deadline:
        controller.step()
        if controller.manager.ready_urls():
            break
        time.sleep(1.0)
    assert controller.manager.ready_urls(), \
        serve_state.get_replicas('mh-svc')
    [url] = controller.manager.ready_urls()
    resp = requests.post(url + '/generate',
                         json={'prompt_ids': [5, 9, 2, 7],
                               'max_new_tokens': 6},
                         timeout=120)
    assert resp.status_code == 200, resp.text
    body = resp.json()
    assert len(body['output_ids']) == 6
    # Deterministic greedy decode through the multi-host engine.
    again = requests.post(url + '/generate',
                          json={'prompt_ids': [5, 9, 2, 7],
                                'max_new_tokens': 6},
                          timeout=120).json()
    assert again['output_ids'] == body['output_ids']
