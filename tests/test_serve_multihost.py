"""Serve e2e: one replica SPANNING MULTIPLE HOSTS of its slice.

The service's replica resources ask for a 4-host TPU slice
(local-cloud emulation: tpu-v5e-16 = 4 host processes; v5e-8 is a
SINGLE 8-chip host in this catalog); the replica task runs the real
serving script on every host under the gang env contract.
The hosts join one jax.distributed process group, decode is sharded over
the global ('tp',) mesh (infer/multihost.py), only the head binds HTTP,
and the replica manager probes/serves through the head — proving a model
bigger than one host's HBM can serve.  Reference capability:
llm/vllm/service.yaml tensor-parallel replicas +
sky/backends/cloud_vm_ray_backend.py:6306 pod-host semantics.
"""
import os
import time

import pytest
import requests

from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.controller import ServeController
from skypilot_tpu.serve.service_spec import ServiceSpec

from tests.test_launch_e2e import iso_state  # noqa: F401  (fixture reuse)

pytestmark = pytest.mark.slow

_SCRIPT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', 'examples', 'scripts',
                 'serve_llama.py'))

# XLA_FLAGS cleared: the pytest conftest's forced-host-device-count leaks
# into spawned ranks and would override --devices-per-host.
_RUN = ('export XLA_FLAGS=; export JAX_PLATFORMS=cpu; '
        f'python {_SCRIPT} --port $SKYPILOT_SERVE_PORT '
        '--model-size tiny-tp --max-seq-len 128 --batch-size 2 '
        '--devices-per-host 1')


def _service_task():
    return task_lib.Task.from_yaml_config({
        'name': 'mh-svc',
        'run': _RUN,
        # tpu-v5e-16 on the local cloud = 4 emulated host processes;
        # the serving script uses 1 virtual CPU device per host, so the
        # global mesh is tp=4 across 4 OS processes.
        'resources': {'cloud': 'local', 'accelerators': 'tpu-v5e-16'},
        'service': {
            'readiness_probe': {'path': '/health',
                                'initial_delay_seconds': 300},
            'replica_policy': {'min_replicas': 1},
            'ports': 18473,
        },
    })


@pytest.fixture()
def mh_service(iso_state):  # noqa: F811
    task = _service_task()
    serve_state.add_service('mh-svc',
                            ServiceSpec.from_yaml_config(
                                task.service).to_yaml_config(),
                            task.to_yaml_config())
    controller = ServeController('mh-svc', probe_interval=1.0)
    yield controller
    controller.stop()
    controller.manager.terminate_all()


def test_multihost_replica_serves(mh_service):
    controller = mh_service
    deadline = time.time() + 300
    while time.time() < deadline:
        controller.step()
        if controller.manager.ready_urls():
            break
        time.sleep(1.0)
    assert controller.manager.ready_urls(), \
        serve_state.get_replicas('mh-svc')
    [url] = controller.manager.ready_urls()
    # The replica REALLY spans 4 host processes: rank 0..3 all alive
    # (a single-host fallback would pass the HTTP checks below —
    # assert the topology, not just the endpoint).
    port = int(url.rsplit(':', 1)[1])
    ranks = {info[1] for info in _scan_rank_pids().values()
             if info[2] == str(port)}
    assert ranks == {'0', '1', '2', '3'}, ranks
    resp = requests.post(url + '/generate',
                         json={'prompt_ids': [5, 9, 2, 7],
                               'max_new_tokens': 6},
                         timeout=120)
    assert resp.status_code == 200, resp.text
    body = resp.json()
    assert len(body['output_ids']) == 6
    # Deterministic greedy decode through the multi-host engine.
    again = requests.post(url + '/generate',
                          json={'prompt_ids': [5, 9, 2, 7],
                                'max_new_tokens': 6},
                          timeout=120).json()
    assert again['output_ids'] == body['output_ids']
    # The OpenAI-compatible surface rides the same multi-host engine.
    oai = requests.post(url + '/v1/completions',
                        json={'prompt': [5, 9, 2, 7], 'max_tokens': 4},
                        timeout=120)
    assert oai.status_code == 200, oai.text
    assert oai.json()['object'] == 'text_completion'
    assert oai.json()['usage']['completion_tokens'] == 4


def _scan_rank_pids():
    """{pid: (cmdline, SKYTPU_PROCESS_ID, SKYPILOT_SERVE_PORT)} for
    every live python serve_llama process (matched via /proc environ:
    the rank's cmdline holds the unexpanded $SKYPILOT_SERVE_PORT)."""
    out = {}
    for pid in os.listdir('/proc'):
        if not pid.isdigit():
            continue
        try:
            with open(f'/proc/{pid}/cmdline', 'rb') as f:
                cmdline = f.read().replace(b'\0', b' ').decode(
                    errors='replace')
            if 'serve_llama.py' not in cmdline or \
                    'python' not in cmdline:
                continue
            with open(f'/proc/{pid}/environ', 'rb') as f:
                env = dict(kv.split('=', 1) for kv in
                           f.read().decode(errors='replace').split('\0')
                           if '=' in kv)
            out[int(pid)] = (cmdline[:80],
                             env.get('SKYTPU_PROCESS_ID'),
                             env.get('SKYPILOT_SERVE_PORT'))
        except (OSError, ValueError):
            continue
    return out


def _find_rank_pid(port: int, rank: int):
    for pid, (_, proc_id, serve_port) in _scan_rank_pids().items():
        if proc_id == str(rank) and serve_port == str(port):
            return pid
    return None


def test_worker_host_death_replaces_replica(mh_service):
    """Chaos: kill one WORKER host of the 4-host replica.  The head's
    idle ping hits the broken control channel, the head hard-exits
    (serve_llama._fatal_if_channel_broken), probes fail, and the
    controller replaces the whole replica — the multi-host failure
    story end to end (reference scope: replica recovery,
    sky/serve/replica_managers.py)."""
    controller = mh_service
    deadline = time.time() + 300
    while time.time() < deadline:
        controller.step()
        if controller.manager.ready_urls():
            break
        time.sleep(1.0)
    assert controller.manager.ready_urls(), \
        serve_state.get_replicas('mh-svc')
    [old] = [r for r in serve_state.get_replicas('mh-svc')
             if r['status'].value == 'READY']

    port = int(old['url'].rsplit(':', 1)[1])
    worker_pid = _find_rank_pid(port, rank=1)
    assert worker_pid is not None, (
        f'worker rank not found for port {port}; '
        f'live: {_scan_rank_pids()}')
    os.kill(worker_pid, 9)   # SIGKILL: an abrupt host loss

    deadline = time.time() + 300
    replaced = False
    while time.time() < deadline:
        controller.step()
        fresh = [r for r in serve_state.get_replicas('mh-svc')
                 if r['status'].value == 'READY'
                 and r['replica_id'] != old['replica_id']]
        if fresh:
            replaced = True
            break
        time.sleep(1.0)
    assert replaced, serve_state.get_replicas('mh-svc')
    # The replacement serves requests.
    [url] = controller.manager.ready_urls()
    resp = requests.post(url + '/generate',
                         json={'prompt_ids': [5, 9, 2],
                               'max_new_tokens': 4}, timeout=120)
    assert resp.status_code == 200, resp.text


def test_multihost_streams_local_checkpoint(iso_state, tmp_path):  # noqa: F811
    """The 70B story in miniature, end to end: a LOCAL safetensors
    checkpoint (2 KV heads) serves from a 4-host replica — every host
    STREAM-converts its shards directly onto the global GQA-overshard
    mesh (tp_kv=2 x tpq=2 across processes; convert.load_hf_model_sharded),
    no host ever holding the full weights."""
    transformers = pytest.importorskip('transformers')
    torch = pytest.importorskip('torch')
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256)
    torch.manual_seed(0)
    ckpt = str(tmp_path / 'ckpt')
    transformers.LlamaForCausalLM(cfg).save_pretrained(
        ckpt, safe_serialization=True)

    run = ('export XLA_FLAGS=; export JAX_PLATFORMS=cpu; '
           f'python {_SCRIPT} --port $SKYPILOT_SERVE_PORT '
           f'--hf-model {ckpt} --max-seq-len 128 --batch-size 2 '
           '--devices-per-host 1')
    task = task_lib.Task.from_yaml_config({
        'name': 'mh-hf',
        'run': run,
        'resources': {'cloud': 'local', 'accelerators': 'tpu-v5e-16'},
        'service': {
            'readiness_probe': {'path': '/health',
                                'initial_delay_seconds': 300},
            'replica_policy': {'min_replicas': 1},
            'ports': 18478,
        },
    })
    serve_state.add_service('mh-hf', ServiceSpec.from_yaml_config(
        task.service).to_yaml_config(), task.to_yaml_config())
    controller = ServeController('mh-hf', probe_interval=1.0)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            controller.step()
            if controller.manager.ready_urls():
                break
            time.sleep(1.0)
        assert controller.manager.ready_urls(), \
            serve_state.get_replicas('mh-hf')
        [url] = controller.manager.ready_urls()
        port = int(url.rsplit(':', 1)[1])
        ranks = {info[1] for info in _scan_rank_pids().values()
                 if info[2] == str(port)}
        assert ranks == {'0', '1', '2', '3'}, ranks
        resp = requests.post(url + '/generate',
                             json={'prompt_ids': [5, 9, 2],
                                   'max_new_tokens': 4}, timeout=120)
        assert resp.status_code == 200, resp.text
        assert len(resp.json()['output_ids']) == 4
    finally:
        controller.stop()
        controller.manager.terminate_all()
