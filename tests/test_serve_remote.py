"""Remote serve-controller mode: with ``serve.controller.resources``
configured, `serve up` ships the service to a dedicated controller
CLUSTER and the serve daemon — replica probes, autoscaling, LB — runs
there, surviving the client (VERDICT r2 missing #2; reference:
sky/templates/sky-serve-controller.yaml.j2 +
sky/serve/service.py:327,:354).

Hermetic: the controller cluster is a `local`-cloud host whose HOME is
the fake host's directory, so the serve DB, daemon pid, and replica
clusters all provably live on the controller, not the client.
"""
import os
import time

import pytest
import requests

from skypilot_tpu import config as config_lib
from skypilot_tpu import state
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus

from tests.test_launch_e2e import iso_state  # noqa: F401  (fixture reuse)
from tests.test_serve import SERVICE_RUN

pytestmark = pytest.mark.slow


@pytest.fixture()
def remote_serve(iso_state):  # noqa: F811
    config_lib.set_nested(('serve', 'controller', 'resources'),
                          {'cloud': 'local'})
    yield iso_state
    # Kill the controller-side serve daemon explicitly (it is detached
    # from every test process by design — that detachment is the point
    # of the feature — so nothing else reaps it).
    import glob
    import signal
    for pid_file in glob.glob(
            str(iso_state) + '/**/serve_controller.pid', recursive=True):
        try:
            with open(pid_file, encoding='utf-8') as f:
                os.kill(int(f.read().strip()), signal.SIGTERM)
        except (ValueError, OSError):
            pass
    config_lib.set_nested(('serve', 'controller', 'resources'), None)


def _service_task():
    return task_lib.Task.from_yaml_config({
        'name': 'remote-svc',
        'run': SERVICE_RUN,
        'resources': {'cloud': 'local'},
        'service': {
            'readiness_probe': {'path': '/',
                                'initial_delay_seconds': 60},
            'replica_policy': {'min_replicas': 1},
            'ports': 8124,
        },
    })


def _wait_ready(timeout=150):
    deadline = time.time() + timeout
    records = []
    while time.time() < deadline:
        records = serve_core.status()
        if records and records[0]['status'] == ServiceStatus.READY and \
                any(r['status'] == ReplicaStatus.READY
                    for r in records[0]['replicas']):
            return records[0]
        time.sleep(2.0)
    raise AssertionError(f'service never READY: {records}')


def test_service_survives_on_controller_cluster(remote_serve):
    endpoint = serve_core.up(_service_task())
    assert endpoint.startswith('http://')

    # The controller cluster exists and is a real provisioned cluster.
    record = state.get_cluster(serve_core.CONTROLLER_CLUSTER)
    assert record is not None
    assert record['status'] == state.ClusterStatus.UP
    host_dir = record['handle'].cluster_info.head.workdir

    # NOTHING serve-related lives on the client: no serve DB rows, no
    # controller daemon pid — killing the client machine loses nothing.
    client_dir = os.path.expanduser('~/.skypilot_tpu')
    assert not os.path.exists(os.path.join(client_dir,
                                           'serve_controller.pid'))
    from skypilot_tpu.serve import serve_state
    assert serve_state.get_services() == []

    # ...while the controller host owns the service end to end.
    assert os.path.exists(os.path.join(host_dir, '.skypilot_tpu',
                                       'serve_controller.pid'))

    svc = _wait_ready()
    assert svc['name'] == 'remote-svc'

    # The LB on the controller actually proxies requests.
    resp = requests.get(svc['endpoint'], timeout=10)
    assert resp.status_code == 200

    # The serve daemon is a detached process on the controller (its own
    # session), not a child of this client process: client death cannot
    # take it down.
    with open(os.path.join(host_dir, '.skypilot_tpu',
                           'serve_controller.pid'),
              encoding='utf-8') as f:
        daemon_pid = int(f.read().strip())
    assert os.getsid(daemon_pid) != os.getsid(os.getpid())

    # Round-trip down: the controller's daemon drains the service.
    serve_core.down('remote-svc')
    deadline = time.time() + 90
    while time.time() < deadline:
        if not serve_core.status():
            break
        time.sleep(2.0)
    assert serve_core.status() == []


def test_update_round_trips(remote_serve):
    serve_core.up(_service_task())
    task = _service_task()
    task.service['replica_policy']['min_replicas'] = 2
    version = serve_core.update(task, 'remote-svc')
    assert version == 2
    records = serve_core.status()
    assert records[0]['version'] == 2
    serve_core.down('remote-svc')
