"""Serving replica process end-to-end: boots the real server script on
the debug model and drives /health + /generate over HTTP."""
import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest


pytestmark = pytest.mark.slow
SCRIPT = os.path.join(os.path.dirname(__file__), '..', 'examples',
                      'scripts', 'serve_llama.py')


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope='module')
def server():
    port = _free_port()
    env = dict(os.environ)
    proc = subprocess.Popen(
        [sys.executable, SCRIPT, '--port', str(port),
         '--model-size', 'debug', '--max-seq-len', '128'],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 120
    while time.time() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read().decode(errors='replace')
            raise RuntimeError(f'server died: {out[-2000:]}')
        try:
            with urllib.request.urlopen(base + '/health', timeout=5) as r:
                if r.status == 200:
                    break
        except (urllib.error.URLError, OSError):
            time.sleep(1.0)
    else:
        proc.kill()
        raise RuntimeError('server never became healthy')
    yield base
    proc.terminate()
    proc.wait(timeout=15)


def test_generate_with_prompt_ids(server):
    status, body = _post(server + '/generate',
                         {'prompt_ids': [1, 2, 3], 'max_new_tokens': 4})
    assert status == 200
    assert len(body['output_ids']) == 4
    assert body['num_generated'] == 4


def test_generate_with_text_prompt(server):
    status, body = _post(server + '/generate',
                         {'prompt': 'hello tpu', 'max_new_tokens': 3})
    assert status == 200
    assert len(body['output_ids']) == 3


def test_generate_missing_prompt_is_400(server):
    status, body = _post(server + '/generate', {'max_new_tokens': 3})
    assert status == 400
    assert 'prompt' in body['error']


def test_generate_malformed_fields_are_400(server):
    for payload in ({'prompt_ids': ['abc']},
                    {'prompt_ids': 5},
                    {'prompt_ids': [1], 'max_new_tokens': 'lots'},
                    {'prompt_ids': [1], 'seed': 'x'}):
        status, body = _post(server + '/generate', payload)
        assert status == 400, payload
        assert 'error' in body


def test_generate_out_of_range_ids_are_400(server):
    status, body = _post(server + '/generate',
                         {'prompt_ids': [128000]})  # debug vocab is 512
    assert status == 400
    assert 'out of range' in body['error']


def test_generate_deterministic_greedy(server):
    a = _post(server + '/generate', {'prompt_ids': [5, 6, 7]})[1]
    b = _post(server + '/generate', {'prompt_ids': [5, 6, 7]})[1]
    assert a['output_ids'] == b['output_ids']


def test_concurrent_requests_continuous_batching(server):
    """Concurrent requests share the decode batch (continuous batching):
    both complete and each matches its solo (greedy) output."""
    import concurrent.futures as cf
    solo = {}
    for ids in ([5, 6, 7], [11, 12]):
        _, body = _post(server + '/generate',
                        {'prompt_ids': ids, 'max_new_tokens': 8})
        solo[tuple(ids)] = body['output_ids']

    with cf.ThreadPoolExecutor(max_workers=2) as ex:
        futs = {tuple(ids): ex.submit(
            _post, server + '/generate',
            {'prompt_ids': list(ids), 'max_new_tokens': 8})
            for ids in solo}
        for ids, fut in futs.items():
            status, body = fut.result(timeout=120)
            assert status == 200
            assert body['output_ids'] == solo[ids], ids


def test_hf_local_checkpoint_streams_onto_tp_mesh(tmp_path):
    """--hf-model <local safetensors dir> with --tp: the server
    stream-converts the checkpoint directly onto the tp shards
    (convert.load_hf_model_sharded) and serves from it."""
    transformers = pytest.importorskip('transformers')
    torch = pytest.importorskip('torch')
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256)
    torch.manual_seed(0)
    model_dir = str(tmp_path / 'ckpt')
    transformers.LlamaForCausalLM(cfg).save_pretrained(
        model_dir, safe_serialization=True)

    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, SCRIPT, '--port', str(port),
         '--hf-model', model_dir, '--tp', '2',
         '--max-seq-len', '128', '--batch-size', '2'],
        env=dict(os.environ), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 180
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError('server died: ' + proc.stdout.read()
                                   .decode(errors='replace')[-2000:])
            try:
                with urllib.request.urlopen(base + '/health',
                                            timeout=5) as r:
                    if r.status == 200:
                        break
            except (urllib.error.URLError, OSError):
                time.sleep(1.0)
        else:
            raise RuntimeError('server never became healthy')
        status, body = _post(base + '/generate',
                             {'prompt_ids': [5, 9, 2], 'max_new_tokens': 4})
        assert status == 200
        assert len(body['output_ids']) == 4
    finally:
        proc.terminate()
        out, _ = proc.communicate(timeout=15)
    # The STREAMING loader must have been the path taken — a silent
    # fallback to the host-RAM torch load would pass /generate too.
    assert b'"load_path": "streamed-sharded"' in out, out[-1500:]
