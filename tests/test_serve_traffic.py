"""Prefix-affinity serving fabric: consistent-hash ring, seeded traffic
generator, virtual-time fleet simulator, prefix_affinity LB policy, and
the SLO autoscaler (serve/traffic/ + serve/load_balancing_policies.py +
serve/autoscalers.py).  Tier-1: the jax-backed simulator tests run tiny
debug-shape fleets and share one module-scoped set of paired runs."""
import random
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.serve import autoscalers as asc
from skypilot_tpu.serve import load_balancing_policies as lbp
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.serve.traffic import generator as gen
from skypilot_tpu.serve.traffic import hashring


# --- hash ring --------------------------------------------------------------

def test_stable_hash_process_stable():
    # blake2b-based: must not depend on PYTHONHASHSEED like hash().
    assert hashring.stable_hash('abc') == hashring.stable_hash('abc')
    assert hashring.stable_hash('abc') != hashring.stable_hash('abd')
    assert 0 <= hashring.stable_hash(b'\x00\x01') < 2 ** 64


def test_ring_owner_walk_yields_distinct_members():
    ring = hashring.ConsistentHashRing()
    ring.set_members([f'r{i}' for i in range(5)])
    owners = list(ring.owners(hashring.stable_hash('key')))
    assert sorted(owners) == sorted(f'r{i}' for i in range(5))


def test_ring_join_remaps_bounded_fraction():
    ring = hashring.ConsistentHashRing()
    members = [f'r{i}' for i in range(8)]
    ring.set_members(members)
    keys = [hashring.stable_hash(f'prompt-{i}') for i in range(2000)]
    before = [ring.primary(k) for k in keys]
    ring.set_members(members + ['r8'])
    after = [ring.primary(k) for k in keys]
    moved = sum(1 for b, a in zip(before, after) if b != a)
    # Ideal remap on 8 -> 9 members is 1/9 of keys; vnode variance gives
    # slack, but nothing like the ~8/9 a naive `hash % n` would move.
    assert moved / len(keys) < 0.3
    # Every moved key moved TO the new member (that's what joining means
    # on a consistent ring).
    assert all(a == 'r8' for b, a in zip(before, after) if b != a)


def test_ring_leave_only_remaps_departed_keys():
    ring = hashring.ConsistentHashRing()
    members = [f'r{i}' for i in range(6)]
    ring.set_members(members)
    keys = [hashring.stable_hash(f'prompt-{i}') for i in range(1000)]
    before = [ring.primary(k) for k in keys]
    ring.set_members([m for m in members if m != 'r3'])
    after = [ring.primary(k) for k in keys]
    for b, a in zip(before, after):
        if b != 'r3':
            assert a == b   # survivors keep their arcs


def test_ring_remove_member_matches_full_rebuild():
    # The replica-death path: in-place removal must land every key
    # exactly where a rebuild without the member would — the two code
    # paths (death vs drain/resync) may never disagree on ownership.
    members = [f'r{i}' for i in range(6)]
    dead = hashring.ConsistentHashRing()
    dead.set_members(members)
    dead.remove_member('r2')
    rebuilt = hashring.ConsistentHashRing()
    rebuilt.set_members([m for m in members if m != 'r2'])
    keys = [hashring.stable_hash(f'prompt-{i}') for i in range(1000)]
    assert [dead.primary(k) for k in keys] == \
        [rebuilt.primary(k) for k in keys]
    assert dead.members == rebuilt.members
    dead.remove_member('r2')            # unknown member: no-op
    assert dead.members == rebuilt.members


def test_ring_death_remap_bounded_and_affinity_recovers():
    # Kill one of 6 members: only the departed arcs remap (~1/6 of
    # keys), each to the next surviving vnode; when the replica heals
    # and rejoins, every key returns to its original owner — the
    # affinity-recovery property that keeps prefix caches warm across
    # a kill + heal cycle.
    ring = hashring.ConsistentHashRing()
    members = [f'r{i}' for i in range(6)]
    ring.set_members(members)
    keys = [hashring.stable_hash(f'prompt-{i}') for i in range(2000)]
    before = [ring.primary(k) for k in keys]
    ring.remove_member('r3')
    after = [ring.primary(k) for k in keys]
    moved = sum(1 for b, a in zip(before, after) if b != a)
    assert 0 < moved / len(keys) < 0.35     # bounded, not a reshuffle
    for b, a in zip(before, after):
        if b != 'r3':
            assert a == b                   # survivors keep their arcs
    ring.add_member('r3')
    assert [ring.primary(k) for k in keys] == before


# --- traffic generator ------------------------------------------------------

def test_trace_seeded_and_sorted():
    cfg = gen.TrafficConfig(seed=3, duration_s=20.0)
    a = gen.generate_trace(cfg)
    b = gen.generate_trace(cfg)
    assert a == b
    assert a != gen.generate_trace(gen.TrafficConfig(seed=4,
                                                     duration_s=20.0))
    assert all(x.t <= y.t for x, y in zip(a, a[1:]))
    assert all(0 <= x.t < cfg.duration_s for x in a)


def test_trace_session_model_shares_heads():
    cfg = gen.TrafficConfig(seed=5, duration_s=30.0, session_share=0.75)
    trace = gen.generate_trace(cfg)
    sessioned = [a for a in trace if a.session is not None]
    singles = [a for a in trace if a.session is None]
    assert sessioned and singles
    heads = {}
    for a in sessioned:
        # All arrivals of one session carry the same head, and the
        # prompt starts with that head verbatim.
        assert heads.setdefault(a.session, a.head) == a.head
        assert len(a.prompt) > cfg.head_tokens
    by_head = {}
    for a in sessioned:
        by_head.setdefault(a.head, set()).add(
            tuple(a.prompt[:cfg.head_tokens]))
    assert all(len(v) == 1 for v in by_head.values())
    for a in trace:
        assert len(a.prompt) <= cfg.max_prompt_tokens
        assert cfg.min_out_tokens <= a.max_new_tokens <= cfg.max_out_tokens


def test_trace_validation():
    with pytest.raises(ValueError):
        gen.TrafficConfig(duration_s=0)
    with pytest.raises(ValueError):
        gen.TrafficConfig(session_share=1.5)
    with pytest.raises(ValueError):
        gen.TrafficConfig(head_tokens=120, max_prompt_tokens=120)


# --- LB policies ------------------------------------------------------------

def test_least_load_tie_break_randomized():
    random.seed(0)
    policy = lbp.LeastLoadPolicy()
    policy.set_ready_replicas(['a', 'b', 'c'])
    # All loads equal: `min` alone would pin every selection to 'a' and
    # a scale-up burst would pile onto one replica.
    picks = {policy.select_replica() for _ in range(60)}
    assert len(picks) > 1
    # Load still dominates: the unloaded replica wins a tie-free pick.
    policy.pre_execute_hook('a')
    policy.pre_execute_hook('b')
    assert policy.select_replica() == 'c'


def test_prefix_affinity_fingerprint_block_granularity():
    policy = lbp.PrefixAffinityPolicy(prefix_block=8,
                                      fingerprint_blocks=2)
    policy.set_ready_replicas(['a', 'b'])
    assert policy.fingerprint(None) is None
    assert policy.fingerprint(list(range(7))) is None    # < one block
    head = list(range(16))
    fp = policy.fingerprint(head + [99, 98])
    assert fp == policy.fingerprint(head + [1, 2, 3])    # tail ignored
    assert fp != policy.fingerprint(list(range(1, 17)))
    # Text path: ~4 chars/token heuristic window (>= 4 * prefix_block).
    assert policy.fingerprint('x' * 32) is not None
    assert policy.fingerprint('x' * 31) is None


def test_prefix_affinity_sticky_and_spread():
    random.seed(0)
    policy = lbp.PrefixAffinityPolicy(prefix_block=8)
    policy.set_ready_replicas([f'r{i}' for i in range(4)])
    heads = [[i * 31 + j for j in range(8)] for i in range(32)]
    first = {i: policy.select_replica({'prompt': h})
             for i, h in enumerate(heads)}
    # Sticky: unloaded fleet always routes a head to its ring owner.
    for i, h in enumerate(heads):
        assert policy.select_replica({'prompt': h}) == first[i]
    # Spread: 32 heads land on more than one replica.
    assert len(set(first.values())) > 1


def test_prefix_affinity_bounded_load_diverts():
    random.seed(0)
    policy = lbp.PrefixAffinityPolicy(prefix_block=8, load_factor=1.25)
    policy.set_ready_replicas(['a', 'b'])
    prompt = list(range(8))
    primary = policy.select_replica({'prompt': prompt})
    other = 'b' if primary == 'a' else 'a'
    hits0, miss0 = policy.affinity_hits, policy.affinity_misses
    # Load the primary past bound = ceil(1.25 * (total+1) / 2).
    for _ in range(5):
        policy.pre_execute_hook(primary)
    assert policy.select_replica({'prompt': prompt}) == other
    assert policy.affinity_misses == miss0 + 1
    # Drain the primary: affinity resumes and counts a hit.
    for _ in range(5):
        policy.post_execute_hook(primary)
    assert policy.select_replica({'prompt': prompt}) == primary
    assert policy.affinity_hits == hits0 + 1


def test_prefix_affinity_short_prompt_falls_back_to_least_load():
    random.seed(0)
    policy = lbp.PrefixAffinityPolicy(prefix_block=64)
    policy.set_ready_replicas(['a', 'b'])
    policy.pre_execute_hook('a')
    miss0 = policy.affinity_misses
    assert policy.select_replica({'prompt': [1, 2, 3]}) == 'b'
    assert policy.select_replica() == 'b'       # no context at all
    assert policy.affinity_misses == miss0 + 2


def test_prefix_affinity_churn_remaps_bounded():
    random.seed(0)
    policy = lbp.PrefixAffinityPolicy(prefix_block=8)
    policy.set_ready_replicas([f'r{i}' for i in range(4)])
    heads = [[i * 17 + j for j in range(8)] for i in range(200)]
    before = [policy.select_replica({'prompt': h}) for h in heads]
    policy.set_ready_replicas([f'r{i}' for i in range(5)])
    after = [policy.select_replica({'prompt': h}) for h in heads]
    moved = sum(1 for b, a in zip(before, after) if b != a)
    # Ideal 4 -> 5 remap is 1/5; a full rehash would move ~4/5.
    assert moved / len(heads) < 0.5


# --- ServiceSpec / autoscaler dispatch --------------------------------------

def test_slo_spec_roundtrip_and_dispatch():
    spec = ServiceSpec.from_yaml_config({
        'readiness_probe': '/health',
        'replica_policy': {'min_replicas': 1, 'max_replicas': 3,
                           'target_p99_ttft_ms': 500,
                           'target_queue_depth_per_replica': 8},
        'load_balancing_policy': 'prefix_affinity',
    })
    assert spec.autoscaling_enabled
    assert spec == ServiceSpec.from_yaml_config(spec.to_yaml_config())
    a = asc.Autoscaler.from_spec('svc', spec)
    assert isinstance(a, asc.SLOAutoscaler)
    assert a.target_p99_ttft_ms == 500
    assert a.target_queue_depth_per_replica == 8
    # QPS spec still dispatches to RequestRateAutoscaler.
    rate = ServiceSpec(min_replicas=1, max_replicas=2,
                       target_qps_per_replica=1.0)
    assert type(asc.Autoscaler.from_spec('svc', rate)) is \
        asc.RequestRateAutoscaler


def test_slo_spec_validation():
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(min_replicas=1, target_p99_ttft_ms=500)  # no max
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(min_replicas=1, max_replicas=2,
                    target_p99_ttft_ms=-1)
    with pytest.raises(exceptions.InvalidServiceSpecError):
        ServiceSpec(min_replicas=1, max_replicas=2,
                    target_p99_ttft_ms=500,
                    target_queue_depth_per_replica=0)


def _slo_spec(**kw):
    base = dict(min_replicas=1, max_replicas=4, target_p99_ttft_ms=500,
                upscale_delay_seconds=40, downscale_delay_seconds=40)
    base.update(kw)
    return ServiceSpec(**base)


def _ready(n):
    return [{'replica_id': i + 1, 'status': asc.ReplicaStatus.READY,
             'launched_at': float(i), 'is_spot': False}
            for i in range(n)]


def test_slo_autoscaler_scales_up_on_sustained_breach():
    a = asc.SLOAutoscaler('svc', _slo_spec())
    assert a.scale_up_threshold == 2    # 40s delay / 20s interval
    # One breached window is NOT enough (hysteresis).
    a.collect_request_information({'ttft_ms': [1000.0] * 20})
    assert a.generate_scaling_decisions(_ready(1)) == []
    assert a.target_num_replicas == 1
    # Second consecutive breach: p99/target = 2 -> multiplicative jump.
    a.collect_request_information({'ttft_ms': [1000.0] * 20})
    ups = a.generate_scaling_decisions(_ready(1))
    assert a.target_num_replicas == 2
    assert [d.operator for d in ups] == \
        [asc.AutoscalerDecisionOperator.SCALE_UP]
    # Samples were consumed: an empty window is pressure 0, and the
    # one stale spike must not replay forever.
    assert a._ttft_ms == []


def test_slo_autoscaler_queue_pressure_counts():
    a = asc.SLOAutoscaler('svc', _slo_spec(upscale_delay_seconds=20))
    # No TTFT samples, but a deep fleet queue: 16 queued vs capacity
    # 1 replica * 4/replica -> pressure capped at 2.
    a.collect_request_information({'queue_depth': 16})
    a.generate_scaling_decisions(_ready(1))
    assert a.target_num_replicas == 2


def test_slo_autoscaler_scales_down_with_hysteresis_and_warmth():
    a = asc.SLOAutoscaler('svc', _slo_spec())
    a.target_num_replicas = 4
    # In-SLO but busy (pressure in [0.5, 1]): hold, not shrink.
    a.collect_request_information({'ttft_ms': [400.0] * 10})
    a.generate_scaling_decisions(_ready(4))
    a.collect_request_information({'ttft_ms': [400.0] * 10})
    a.generate_scaling_decisions(_ready(4))
    assert a.target_num_replicas == 4
    # Idle + WARM caches: sheds at most one replica per decision pair.
    a.collect_request_information({'prefix_hit_ratio': 0.9})
    a.generate_scaling_decisions(_ready(4))
    assert a.target_num_replicas == 4   # first under-threshold pass
    a.generate_scaling_decisions(_ready(4))
    assert a.target_num_replicas == 3   # second pass: -1, not -> min
    # Cold caches: idle pressure drops straight toward min_replicas.
    b = asc.SLOAutoscaler('svc', _slo_spec())
    b.target_num_replicas = 4
    b.collect_request_information({'prefix_hit_ratio': 0.0})
    b.generate_scaling_decisions(_ready(4))
    b.generate_scaling_decisions(_ready(4))
    assert b.target_num_replicas == 1


def test_slo_autoscaler_dump_load_roundtrip():
    a = asc.SLOAutoscaler('svc', _slo_spec())
    a.target_num_replicas = 3
    a.upscale_counter = 1
    a.downscale_counter = 0
    states = a.dump_dynamic_states()
    b = asc.SLOAutoscaler('svc', _slo_spec())
    b.load_dynamic_states(states)
    assert b.target_num_replicas == 3
    assert b.upscale_counter == 1
    info = b.info()
    assert info['target_p99_ttft_ms'] == 500


def test_request_rate_qps_cold_start_clamp():
    a = asc.RequestRateAutoscaler(
        'svc', ServiceSpec(min_replicas=1, max_replicas=4,
                           target_qps_per_replica=1.0))
    now = time.time()
    # 10 requests over the last 2 seconds: true rate ~5 qps.  The old
    # full-window denominator reported 10/60 ~ 0.17 qps and suppressed
    # the initial scale-up.
    a.collect_request_information(
        {'timestamps': [now - 2.0 + 0.2 * i for i in range(10)]})
    assert a.current_qps() > 3.0


# --- simulator (jax-backed, tiny debug fleets) ------------------------------

@pytest.fixture(scope='module')
def paired_runs():
    """Three small runs on ONE contended trace: least_load once,
    prefix_affinity twice (the pair locks determinism, the cross-policy
    compare locks the affinity win)."""
    from skypilot_tpu.serve.traffic.simulator import (FleetSimulator,
                                                      SimConfig)
    traffic = gen.TrafficConfig(seed=11, duration_s=10.0, base_rps=8.0,
                                num_sessions=8, num_heads=6,
                                head_tokens=64, session_share=0.85)

    def run(policy):
        sim = FleetSimulator(
            SimConfig(policy=policy, num_replicas=3, batch_size=2,
                      decode_chunk=4, slo_ttft_s=1.5,
                      prefill_cost_per_token_s=4e-3,
                      # ~2 head blocks per replica vs 6 shared heads:
                      # scattered routing must thrash, affinity fits.
                      prefix_cache_mb=0.25),
            traffic)
        return sim.run()

    return run('least_load'), run('prefix_affinity'), \
        run('prefix_affinity')


def test_simulator_summary_deterministic(paired_runs):
    _, affinity_a, affinity_b = paired_runs
    assert affinity_a == affinity_b


def test_affinity_beats_least_load_when_cache_contended(paired_runs):
    least, affinity, _ = paired_runs
    assert least['requests'] == affinity['requests'] > 0
    assert affinity['prefix_hit_ratio'] > least['prefix_hit_ratio']
    assert affinity['affinity_hit_ratio'] is not None
    assert affinity['goodput_rps'] >= least['goodput_rps']


def test_simulator_drives_real_batcher_prefix_path(paired_runs):
    _, affinity, _ = paired_runs
    # Warm replicas really installed cached head blocks: the saved
    # tokens can only come from ContinuousBatcher's admission path.
    assert affinity['prefix_tokens_saved'] > 0
    assert affinity['slo_attainment'] is not None


def test_slo_autoscaler_scales_up_in_simulator():
    from skypilot_tpu.serve.traffic.simulator import (FleetSimulator,
                                                      SimConfig)
    # Undersized fleet + expensive prefill: p99 TTFT breaches the 300ms
    # target from the first virtual decision window, so the (1-decision
    # hysteresis) autoscaler must grow the fleet mid-trace.
    traffic = gen.TrafficConfig(seed=2, duration_s=45.0, base_rps=1.5,
                                num_sessions=4, num_heads=2)
    sim = FleetSimulator(
        SimConfig(policy='least_load', num_replicas=1, batch_size=2,
                  decode_chunk=4, prefill_cost_per_token_s=10e-3,
                  prefix_cache_mb=None),
        traffic)
    autoscaler = asc.SLOAutoscaler(
        'sim', ServiceSpec(min_replicas=1, max_replicas=2,
                           target_p99_ttft_ms=300,
                           upscale_delay_seconds=20,
                           downscale_delay_seconds=1200))
    summary = sim.run(autoscaler=autoscaler)
    assert autoscaler.target_num_replicas == 2
    assert summary['replicas'] == 2
    assert any(e['replicas'] == 2 for e in summary['scale_events'])
    assert summary['requests'] > 0
