"""SFT: prompt-masked loss + JSONL data path (train/sft.py) and the
end-to-end script on the debug model."""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.train import sft


CFG = llama.LLAMA_DEBUG


def test_encode_example_mask_covers_completion_only():
    tokens, mask = sft.encode_example([1, 2, 3], [4, 5], seq_len=8)
    np.testing.assert_array_equal(tokens[:5], [1, 2, 3, 4, 5])
    # Targets are tokens[1:]; positions 2,3 predict 4,5 (the completion).
    np.testing.assert_array_equal(mask, [0, 0, 1, 1, 0, 0, 0, 0])


def test_encode_example_truncates():
    tokens, mask = sft.encode_example([1, 2], [3, 4, 5, 6], seq_len=4)
    np.testing.assert_array_equal(tokens, [1, 2, 3, 4, 5])
    np.testing.assert_array_equal(mask, [0, 1, 1, 1])


def test_sft_loss_ignores_prompt_tokens():
    """Changing PROMPT content must not change the masked loss
    contribution pattern: loss with mask == manual masked mean of
    per-token logprobs."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                CFG.vocab_size)
    mask = np.zeros((2, 16), np.float32)
    mask[:, 5:12] = 1.0
    batch = {'tokens': tokens, 'loss_mask': jnp.asarray(mask)}
    loss = float(sft.sft_loss_fn(params, batch, CFG))
    logits = llama.forward(params, tokens[:, :-1], CFG)
    lp = np.asarray(llama.token_logprobs(logits, tokens[:, 1:]))
    manual = -(lp * mask).sum() / mask.sum()
    np.testing.assert_allclose(loss, manual, rtol=1e-5)


def test_sft_loss_chunked_matches_full():
    import dataclasses
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 33), 0,
                                CFG.vocab_size)
    mask = np.zeros((2, 32), np.float32)
    mask[:, 3:20] = 1.0
    batch = {'tokens': tokens, 'loss_mask': jnp.asarray(mask)}
    full = float(sft.sft_loss_fn(params, batch, CFG))
    chunked = float(sft.sft_loss_fn(
        params, batch, dataclasses.replace(CFG, loss_chunk=8)))
    np.testing.assert_allclose(chunked, full, rtol=1e-5)


def test_sft_batches_roundtrip(tmp_path):
    path = tmp_path / 'data.jsonl'
    with open(path, 'w', encoding='utf-8') as f:
        for i in range(3):
            f.write(json.dumps({'prompt': f'q{i}',
                                'completion': f'a{i}'}) + '\n')
    it = sft.sft_batches(str(path), lambda t: [ord(c) % 256 for c in t],
                         batch_size=4, seq_len=8, eos_id=7)
    batch = next(it)
    assert batch['tokens'].shape == (4, 9)
    assert batch['loss_mask'].shape == (4, 8)
    assert batch['loss_mask'].sum() > 0


def test_sft_batches_rejects_bad_jsonl(tmp_path):
    path = tmp_path / 'bad.jsonl'
    path.write_text(json.dumps({'prompt': 'only'}) + '\n')
    with pytest.raises(ValueError, match='completion'):
        sft.load_jsonl(str(path))


@pytest.mark.slow
def test_sft_script_end_to_end(tmp_path):
    """The real script: debug model, JSONL data, loss decreases."""
    data = tmp_path / 'sft.jsonl'
    with open(data, 'w', encoding='utf-8') as f:
        for _ in range(8):
            f.write(json.dumps({'prompt': 'what is tpu? ',
                                'completion': 'a matrix machine'}) + '\n')
    script = os.path.join(os.path.dirname(__file__), '..', 'examples',
                          'scripts', 'train_sft.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu', XLA_FLAGS='')
    proc = subprocess.run(
        [sys.executable, script, '--data-file', str(data),
         '--seq-len', '32', '--steps', '12', '--batch-size', '2',
         '--learning-rate', '1e-3', '--log-every', '1'],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'SFT done.' in proc.stdout
    losses = [float(line.rsplit('loss=', 1)[1])
              for line in proc.stdout.splitlines() if 'loss=' in line]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses


def test_sft_loss_moe_trains():
    """sft_loss_fn routes Mixtral-family configs through the MoE trunk
    (router aux included) and the loss decreases under SGD."""
    from skypilot_tpu.models import moe
    cfg = moe.MoeConfig(vocab_size=64, d_model=32, n_layers=2,
                        n_heads=4, n_kv_heads=2, d_ff=48,
                        max_seq_len=64, n_experts=4, top_k=2,
                        dtype=jnp.float32, remat=False,
                        router_impl='dense')
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tokens = np.tile(np.arange(9, dtype=np.int32)[None], (2, 1))
    mask = np.ones((2, 8), np.float32)
    batch = {'tokens': jnp.asarray(tokens),
             'loss_mask': jnp.asarray(mask)}

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(
            lambda p: sft.sft_loss_fn(p, batch, cfg))(p)
        return loss, jax.tree.map(lambda a, b: a - 0.05 * b, p, g)

    losses = []
    for _ in range(8):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
