"""Speculative decoding (infer/spec_decode.py): bit-exactness of the
draft-verify path against sequential decode, distribution preservation
for sampled rows, rollback block-pool accounting, the
one-host-sync-per-chunk contract, and the verify compile budget.

Host-level units (drafter, policy, accept/rollback math) run in tier-1;
model-level end-to-end checks are marked slow like their peers in
test_infer.py / test_continuous_batching.py.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import sampling
from skypilot_tpu.infer import spec_decode
from skypilot_tpu.infer.engine import Generator, GeneratorConfig
from skypilot_tpu.infer.serving import ContinuousBatcher
from skypilot_tpu.metrics import REGISTRY
from skypilot_tpu.models import llama

CFG_F32 = llama.LlamaConfig(vocab_size=128, d_model=64, n_layers=2,
                            n_heads=4, n_kv_heads=2, d_ff=128,
                            max_seq_len=64, dtype=jnp.float32)
CFG_BF16 = llama.LlamaConfig(vocab_size=128, d_model=64, n_layers=2,
                             n_heads=4, n_kv_heads=2, d_ff=128,
                             max_seq_len=64, dtype=jnp.bfloat16)

# Repetitive prompts so the n-gram drafter gets real acceptance (and
# therefore real rollbacks at the repetition boundaries).
PROMPTS = [[5, 6, 7, 5, 6, 7, 5, 6], [9, 9, 9, 9]]


@pytest.fixture(scope='module')
def params_f32():
    return llama.init_params(CFG_F32, jax.random.PRNGKey(0))


@pytest.fixture(scope='module')
def params_bf16():
    return llama.init_params(CFG_BF16, jax.random.PRNGKey(0))


def _gen_config(spec, **kw):
    base = dict(max_seq_len=64, batch_size=2, temperature=0.0,
                decode_impl='pooled', decode_chunk=4, spec_k=spec,
                prefix_cache_mb=1, prefix_block=8)
    base.update(kw)
    return GeneratorConfig(**base)


def _accept_delta():
    return (REGISTRY.get_sample_value(
                'skytpu_infer_spec_accepted_tokens_total') or 0.0,
            REGISTRY.get_sample_value(
                'skytpu_infer_spec_proposed_tokens_total') or 0.0)


# ---------------------------------------------------------------------------
# Host-level units (tier-1)
# ---------------------------------------------------------------------------

def test_drafter_ngram_repetitive():
    d = spec_decode.NgramDrafter(1, 3)
    d.reset(0, [4, 5, 6, 4, 5, 6, 4, 5])
    assert d.propose(0) == [6, 4, 5]


def test_drafter_golden_future_replay_and_divergence():
    d = spec_decode.NgramDrafter(1, 4)
    d.reset(0, [1, 2, 3], continuation=[7, 8, 9, 7, 8, 9, 7, 8])
    # Verbatim replay while the stream matches the cached continuation.
    assert d.propose(0) == [7, 8, 9, 7]
    d.observe(0, [7, 8])
    assert d.propose(0) == [9, 7, 8, 9]
    # First divergence drops the future for good...
    d.observe(0, [5])
    assert d._future[0] == []
    # ...and the n-gram backoff still drafts a full-k window.
    assert len(d.propose(0)) == 4


def test_drafter_batch_masks_dead_slots():
    d = spec_decode.NgramDrafter(3, 2)
    d.reset(1, [4, 5, 4, 5])
    draft = d.propose_batch([1], 3)
    assert draft.shape == (3, 2)
    assert list(draft[1]) == [4, 5]
    assert draft[0].sum() == 0 and draft[2].sum() == 0


def test_policy_backs_off_after_one_bad_chunk_then_probes():
    p = spec_decode.SpecPolicy()
    assert p.should_speculate()          # starts optimistic
    p.record(0, 12)                      # one near-zero chunk
    assert p.ema < p.threshold
    assert p.should_speculate()          # first low-EMA call is a probe
    for _ in range(p.probe_period):      # then sequential until re-probe
        assert not p.should_speculate()
    assert p.should_speculate()


def test_policy_tolerates_one_mediocre_chunk():
    p = spec_decode.SpecPolicy()
    p.record(6, 12)                      # rate 0.5 in a good stream
    assert p.ema >= p.threshold
    assert p.should_speculate()


def test_accept_prefix_len():
    targets = jnp.array([[1, 2, 3, 9], [4, 5, 6, 7], [8, 0, 0, 0]],
                        jnp.int32)
    draft = jnp.array([[1, 2, 5], [4, 5, 6], [9, 0, 0]], jnp.int32)
    got = sampling._accept_prefix_len(targets, draft)
    assert list(np.asarray(got)) == [2, 3, 0]


def test_accept_window_commit_rollback_eos_limit():
    targets = jnp.array([[10, 11, 12, 13],
                         [20, 21, 22, 23],
                         [30, 31, 32, 33],
                         [40, 41, 42, 43]], jnp.int32)
    accepts = jnp.array([2, 0, 3, 3], jnp.int32)
    done = jnp.array([False, False, False, True])
    limit = jnp.array([10, 10, 2, 10], jnp.int32)
    positions = jnp.array([5, 7, 3, 9], jnp.int32)
    token = jnp.array([1, 2, 3, 4], jnp.int32)
    emitted, token, positions, done, limit, committed = (
        spec_decode.accept_window(targets, accepts, done, limit,
                                  positions, token, eos=20,
                                  fill=jnp.int32(0)))
    # Row 0: 2 accepted drafts + the correction token commit.
    # Row 1: correction token only (accepts=0), and it is EOS -> done.
    # Row 2: limit=2 stops the lane after two commits despite accepts=3.
    # Row 3: dead lane frozen entirely.
    assert list(np.asarray(committed)) == [3, 1, 2, 0]
    assert list(np.asarray(positions)) == [8, 8, 5, 9]
    assert list(np.asarray(token)) == [12, 20, 31, 4]
    assert list(np.asarray(done)) == [False, True, True, True]
    assert list(np.asarray(emitted[0])) == [10, 11, 12, 0]
    assert list(np.asarray(emitted[1])) == [20, 0, 0, 0]
    assert list(np.asarray(emitted[2])) == [30, 31, 0, 0]
    assert list(np.asarray(emitted[3])) == [0, 0, 0, 0]


def test_spec_targets_independent_of_draft():
    """The sampled accept draws the target's token at every window
    position from the target distribution alone — the draft gates only
    the accepted-prefix length, never the sampled values."""
    rng = jax.random.PRNGKey(7)
    logits = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 16))
    ones = jnp.ones((2,), jnp.float32)
    t_a, _ = sampling.spec_accept_sampled(
        logits, jnp.zeros((2, 3), jnp.int32), rng, ones, ones)
    t_b, _ = sampling.spec_accept_sampled(
        logits, jnp.full((2, 3), 9, jnp.int32), rng, ones, ones)
    assert np.array_equal(np.asarray(t_a), np.asarray(t_b))


def test_spec_accept_sampled_matches_target_distribution():
    """Monte Carlo: the first committed token's marginal equals the
    target softmax (the distribution-preservation contract)."""
    vocab, n = 8, 2000
    logits = jax.random.normal(jax.random.PRNGKey(3), (1, 2, vocab))
    ones = jnp.ones((1,), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(11), n)
    draft = jnp.zeros((1, 1), jnp.int32)

    def draw(key):
        targets, _ = sampling.spec_accept_sampled(
            logits, draft, key, ones, ones)
        return targets[0, 0]

    toks = np.asarray(jax.vmap(draw)(keys))
    emp = np.bincount(toks, minlength=vocab) / n
    want = np.asarray(jax.nn.softmax(logits[0, 0]))
    assert np.abs(emp - want).sum() < 0.1


def test_spec_k_config_validation():
    with pytest.raises(ValueError):
        GeneratorConfig(spec_k=-1)
    with pytest.raises(ValueError):
        GeneratorConfig(spec_k=3, decode_impl='inplace')
    with pytest.raises(ValueError):
        GeneratorConfig(spec_k=63, max_seq_len=64, decode_impl='pooled')


# ---------------------------------------------------------------------------
# Model-level end-to-end (slow, CPU debug shapes)
# ---------------------------------------------------------------------------

def _seeded_spec_gen(params, cfg, gc, prompts, ref):
    """Spec-on generator whose radix trie already holds each prompt's
    greedy continuation, so admission hands the drafter a golden future
    and the verify/accept/rollback path really runs."""
    g = Generator(params, cfg, gc)
    g.generate([p + o for p, o in zip(prompts, ref)], max_new_tokens=1)
    return g


@pytest.mark.slow
@pytest.mark.parametrize('kv_dtype', [None, 'int8'])
@pytest.mark.parametrize('dtype_name', ['f32', 'bf16'])
def test_generator_greedy_parity(dtype_name, kv_dtype, request):
    """Spec-on greedy output is BIT-EXACT vs spec-off — per param dtype
    (f32/bf16) and KV dtype (model/bf16 vs quantized int8)."""
    cfg = CFG_F32 if dtype_name == 'f32' else CFG_BF16
    params = request.getfixturevalue(f'params_{dtype_name}')
    ref = Generator(params, cfg, _gen_config(0, kv_cache_dtype=kv_dtype)
                    ).generate(PROMPTS, max_new_tokens=20)
    g1 = _seeded_spec_gen(params, cfg,
                          _gen_config(3, kv_cache_dtype=kv_dtype),
                          PROMPTS, ref)
    a0, p0 = _accept_delta()
    out = g1.generate(PROMPTS, max_new_tokens=20)
    a1, p1 = _accept_delta()
    assert out == ref
    assert p1 > p0 and a1 > a0   # the spec path actually ran + accepted


@pytest.mark.slow
def test_batcher_greedy_parity_with_slot_reuse(params_f32):
    """Spec-on ContinuousBatcher matches spec-off token-for-token,
    including a request admitted by slot handoff (3 requests, 2 slots)
    and a prefix-hit re-submission of an earlier prompt."""
    prompts = PROMPTS + [[5, 6, 7, 5, 6, 7, 5, 6], [1, 2, 3, 4]]

    def run(spec):
        b = ContinuousBatcher(params_f32, CFG_F32, _gen_config(spec))
        rids = [b.submit(p, max_new_tokens=16) for p in prompts]
        b.run_until_idle()
        return [b.result(r) for r in rids]

    ref = run(0)
    a0, p0 = _accept_delta()
    assert run(3) == ref
    a1, p1 = _accept_delta()
    assert p1 > p0 and a1 > a0


@pytest.mark.slow
def test_spec_k_zero_is_noop(params_f32):
    g = Generator(params_f32, CFG_F32, _gen_config(0))
    b = ContinuousBatcher(params_f32, CFG_F32, _gen_config(0))
    assert g._drafter is None and b._drafter is None
    assert not hasattr(g, '_verify_chunk') or g.gen.spec_k == 0


@pytest.mark.slow
def test_sampled_spec_preserves_distribution(params_f32):
    """Statistical check at the engine level: with temperature>0 the
    first decode-committed token has the same distribution spec-on and
    spec-off (committed tokens are unbiased draws from the target)."""
    prompt = [5, 6, 7, 5, 6, 7, 5, 6]
    seeds = 60

    def hist(spec):
        gc = _gen_config(spec, batch_size=4, temperature=1.0, top_k=8)
        g = Generator(params_f32, CFG_F32, gc)
        counts = np.zeros(CFG_F32.vocab_size)
        for seed in range(seeds):
            outs = g.generate([prompt] * 4, max_new_tokens=2, seed=seed)
            for o in outs:
                counts[o[1]] += 1
        return counts / counts.sum()

    h_off = hist(0)
    a0, p0 = _accept_delta()
    h_on = hist(3)
    _, p1 = _accept_delta()
    assert p1 > p0                       # speculation really happened
    assert np.abs(h_on - h_off).sum() < 0.35


@pytest.mark.slow
def test_rollback_pool_accounting_exact(params_f32):
    """Rollback is pure cursor math: the free list and refcounts after a
    spec-on run are indistinguishable from the spec-off run, the pool
    invariant (free + live == n_blocks - 1, no duplicate free ids, no
    refcount drift) holds after EVERY step, and prefix-cache shares
    survive rejected tails (one request is a prefix-hit resubmission)."""
    prompts = PROMPTS + [[5, 6, 7, 5, 6, 7, 5, 6], [1, 2, 3, 4]]

    def drive(spec):
        b = ContinuousBatcher(params_f32, CFG_F32, _gen_config(spec))
        rids = [b.submit(p, max_new_tokens=12) for p in prompts]
        for _ in range(400):
            if b.num_active == 0 and b.num_queued == 0:
                break
            b.step()
            b.pool.check_invariant()
        b.pool.check_invariant()
        return b, [b.result(r) for r in rids]

    b0, out0 = drive(0)
    b1, out1 = drive(3)
    assert out1 == out0
    assert len(b1.pool._free) == len(b0.pool._free)
    assert (sorted(b1.pool._refs.tolist())
            == sorted(b0.pool._refs.tolist()))


@pytest.mark.slow
def test_spec_host_sync_budget(params_f32):
    """A verify chunk costs exactly ONE counted host_fetch, like a
    sequential chunk: with win == decode_chunk and a fully seeded
    drafter, spec-on uses no more syncs than spec-off for the same
    token stream."""
    def count(gen, prompts, n):
        calls = [0]
        orig = engine_lib.host_fetch

        def counting(*arrays):
            calls[0] += 1
            return orig(*arrays)

        engine_lib.host_fetch = counting
        try:
            out = gen.generate(prompts, max_new_tokens=n)
        finally:
            engine_lib.host_fetch = orig
        return out, calls[0]

    g0 = Generator(params_f32, CFG_F32, _gen_config(0))
    ref, syncs_off = count(g0, PROMPTS, 16)
    g1 = _seeded_spec_gen(params_f32, CFG_F32, _gen_config(3),
                          PROMPTS, ref)
    out, syncs_on = count(g1, PROMPTS, 16)
    assert out == ref
    assert syncs_on <= syncs_off


@pytest.mark.slow
def test_verify_compile_budget(params_f32):
    """One verify program, and the sequential decode budget (<=2) is
    not disturbed by speculation — across spec chunks, fallback chunks,
    and a second workload."""
    g = _seeded_spec_gen(
        params_f32, CFG_F32, _gen_config(3), PROMPTS,
        Generator(params_f32, CFG_F32, _gen_config(0)).generate(
            PROMPTS, max_new_tokens=16))
    g.generate(PROMPTS, max_new_tokens=16)
    g.generate([[44, 45], [46, 47, 48]], max_new_tokens=8)  # cold drafter
    assert g._verify_chunk._cache_size() <= 1
    assert g._decode_chunk._cache_size() <= 2
