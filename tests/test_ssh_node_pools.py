"""SSH node pools: pool CRUD, host claiming, the ssh provisioner, and the
Ssh cloud (analog of the reference's BYO `ssh` cloud over
~/.sky/ssh_node_pools.yaml)."""
import pytest

from tests.test_launch_e2e import iso_state  # noqa: F401


pytestmark = pytest.mark.slow
POOL = {
    'user': 'ubuntu',
    'identity_file': '~/.ssh/id_rsa',
    'hosts': ['10.0.0.1', '10.0.0.2',
              {'ip': '10.0.0.3', 'user': 'admin', 'ssh_port': 2222}],
}


@pytest.fixture()
def pool_manager(iso_state):  # noqa: F811
    from skypilot_tpu.ssh_node_pools.core import SSHNodePoolManager
    manager = SSHNodePoolManager()
    manager.update_pool('rack-a', dict(POOL))
    return manager


def test_pool_crud(pool_manager):
    from skypilot_tpu import exceptions
    assert 'rack-a' in pool_manager.get_all_pools()
    hosts = pool_manager.pool_hosts('rack-a')
    assert [h['ip'] for h in hosts] == ['10.0.0.1', '10.0.0.2', '10.0.0.3']
    # Pool-wide defaults + per-host overrides.
    assert hosts[0]['user'] == 'ubuntu' and hosts[0]['ssh_port'] == 22
    assert hosts[2]['user'] == 'admin' and hosts[2]['ssh_port'] == 2222
    with pytest.raises(exceptions.InvalidTaskError):
        pool_manager.get_pool('nope')
    with pytest.raises(exceptions.InvalidTaskError):
        pool_manager.update_pool('bad', {'hosts': []})
    pool_manager.delete_pool('rack-a')
    assert pool_manager.get_all_pools() == {}


def test_claim_release_cycle(pool_manager):
    from skypilot_tpu import exceptions
    claimed = pool_manager.claim_hosts('rack-a', 'c1', 2)
    assert [h['ip'] for h in claimed] == ['10.0.0.1', '10.0.0.2']
    # Idempotent for the same cluster (relaunch path).
    again = pool_manager.claim_hosts('rack-a', 'c1', 2)
    assert again == claimed
    # Remaining capacity: 1 host.
    with pytest.raises(exceptions.ResourcesUnavailableError):
        pool_manager.claim_hosts('rack-a', 'c2', 2)
    pool_manager.claim_hosts('rack-a', 'c2', 1)
    # Pool delete blocked while claims exist.
    with pytest.raises(exceptions.InvalidTaskError):
        pool_manager.delete_pool('rack-a')
    pool_manager.release_hosts('c1')
    pool_manager.release_hosts('c2')
    pool_manager.delete_pool('rack-a')


def test_ssh_provisioner_api(pool_manager):
    from skypilot_tpu import provision as provision_api
    record = provision_api.run_instances(
        'ssh', 'rack-a', 'c1', {'pool': 'rack-a', 'num_hosts': 2})
    assert record.head_instance_id == '10.0.0.1'
    info = provision_api.get_cluster_info('ssh', 'rack-a', 'c1')
    assert info.num_hosts == 2
    assert info.ssh_user == 'ubuntu'
    assert info.ssh_key_path == '~/.ssh/id_rsa'
    assert info.head.external_ip == '10.0.0.1'
    # Unreachable fake hosts report 'stopped'.
    statuses = provision_api.query_instances('ssh', 'c1')
    assert set(statuses) == {'10.0.0.1', '10.0.0.2'}
    provision_api.terminate_instances('ssh', 'c1')
    assert pool_manager.get_claim('c1') is None
    with pytest.raises(NotImplementedError):
        provision_api.stop_instances('ssh', 'c1')


def test_ssh_cloud_feasibility(pool_manager):
    from skypilot_tpu.clouds import Ssh
    from skypilot_tpu.resources import Resources
    cloud = Ssh()
    ok, _ = cloud.check_credentials()
    assert ok
    # Not requested -> not feasible (never competes with real clouds).
    feasible = cloud.get_feasible_launchable_resources(Resources())
    assert feasible.resources_list == []
    feasible = cloud.get_feasible_launchable_resources(
        Resources(cloud='ssh'))
    assert len(feasible.resources_list) == 1
    choice = feasible.resources_list[0]
    assert choice.region == 'rack-a'
    assert cloud.get_hourly_cost(choice) == 0.0
    regions = list(cloud.region_zones_provision_loop(Resources(cloud='ssh')))
    assert regions == [('rack-a', [None])]
    deploy = cloud.make_deploy_resources_variables(
        choice, 'c1', 'rack-a', None)
    assert deploy['pool'] == 'rack-a' and deploy['num_hosts'] == 1


def test_ssh_cloud_no_pools(iso_state):  # noqa: F811
    from skypilot_tpu.clouds import Ssh
    ok, reason = Ssh().check_credentials()
    assert not ok and 'No SSH node pools' in reason


def test_check_probes_all_clouds(pool_manager):
    """`skytpu check` probes every registered cloud (regression: Registry
    lacked .items() and check crashed)."""
    from skypilot_tpu import check as check_lib
    results = check_lib.check(quiet=True)
    assert {'gcp', 'kubernetes', 'local', 'ssh'} <= set(results)
    assert results['local']['enabled']
    assert results['ssh']['enabled']          # pool_manager configured one
    enabled = check_lib.get_cached_enabled_clouds()
    assert 'local' in enabled and 'ssh' in enabled
