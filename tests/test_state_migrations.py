"""Versioned state-DB migrations (VERDICT r1 missing #7; reference:
alembic runner sky/utils/db/migration_utils.py)."""
import sqlite3

from skypilot_tpu.utils import db_utils


def _old_db(path):
    """A pre-migration round-0 DB: clusters without workspace columns."""
    conn = sqlite3.connect(path)
    conn.executescript('''
        CREATE TABLE clusters (name TEXT PRIMARY KEY, launched_at REAL,
            handle_json TEXT, status TEXT, last_use TEXT,
            autostop_json TEXT, to_down INTEGER DEFAULT 0);
        CREATE TABLE cluster_history (name TEXT, launched_at REAL,
            torn_down_at REAL, resources TEXT, duration_s REAL);
        CREATE TABLE storage (name TEXT PRIMARY KEY, store TEXT,
            mode TEXT, last_attached_cluster TEXT, created_at REAL);
    ''')
    conn.execute("INSERT INTO clusters (name, status) VALUES ('old', 'UP')")
    conn.commit()
    return conn


def test_upgrade_old_db_to_head(tmp_path):
    path = str(tmp_path / 'state.db')
    conn = _old_db(path)
    from skypilot_tpu import state
    version = db_utils.migrate_to_head(conn, state._MIGRATIONS)
    assert version == len(state._MIGRATIONS)
    cols = {r[1] for r in conn.execute('PRAGMA table_info(clusters)')}
    assert {'workspace', 'user_hash'} <= cols
    # Existing rows survive with defaults.
    row = conn.execute(
        "SELECT workspace FROM clusters WHERE name='old'").fetchone()
    assert row[0] in ('default', None)


def test_migrations_idempotent_and_recorded(tmp_path):
    path = str(tmp_path / 'state.db')
    conn = _old_db(path)
    from skypilot_tpu import state
    db_utils.migrate_to_head(conn, state._MIGRATIONS)
    v1 = conn.execute('SELECT MAX(version) FROM schema_version'
                      ).fetchone()[0]
    # Second run: no-op, version unchanged.
    db_utils.migrate_to_head(conn, state._MIGRATIONS)
    v2 = conn.execute('SELECT MAX(version) FROM schema_version'
                      ).fetchone()[0]
    assert v1 == v2 == len(state._MIGRATIONS)


def test_new_migration_applies_from_recorded_version(tmp_path):
    path = str(tmp_path / 'state.db')
    conn = _old_db(path)
    from skypilot_tpu import state
    db_utils.migrate_to_head(conn, state._MIGRATIONS)
    applied = []

    def _v_next(c):
        applied.append(True)
        c.execute('CREATE TABLE IF NOT EXISTS new_feature (x TEXT)')

    extended = list(state._MIGRATIONS) + [_v_next]
    db_utils.migrate_to_head(conn, extended)
    assert applied == [True]           # only the NEW migration ran
    db_utils.migrate_to_head(conn, extended)
    assert applied == [True]           # and only once


def test_fresh_db_through_state_module(tmp_path, monkeypatch, tmp_home):
    """state._conn on a fresh DB lands at head version."""
    from skypilot_tpu import state
    monkeypatch.setattr(state, '_migrated_paths', set())
    with state._conn() as conn:
        v = conn.execute('SELECT MAX(version) FROM schema_version'
                         ).fetchone()[0]
        assert v == len(state._MIGRATIONS)
