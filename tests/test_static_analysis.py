"""Tier-1 wiring for skytpu-lint (skypilot_tpu/analysis/).

Three layers:

1. **Rule units**: every linter rule fires on a known-bad snippet and
   stays quiet on the sanctioned pattern next to it.
2. **Package gate**: `skypilot_tpu/` lints clean against the checked-in
   baseline, and the baseline itself can shrink but never grow.
3. **Auditor**: the decode chunk compiles exactly once per cache bucket
   and donates its KV cache — plus the NEGATIVE directions: a synthetic
   ``int(tracer)`` in the decode body must surface as a lint violation
   AND an audit failure, and an extra per-bucket recompile must breach
   the compile budget.
"""
import os
import textwrap

import jax
import pytest

from skypilot_tpu.analysis import baseline as baseline_lib
from skypilot_tpu.analysis import linter

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_ROOT = os.path.join(REPO_ROOT, 'skypilot_tpu')


def codes(source: str, path: str = 'infer/somefile.py'):
    return [v.code for v in linter.lint_source(textwrap.dedent(source),
                                               path)]


# ---------------------------------------------------------------------------
# 1. Rule units
# ---------------------------------------------------------------------------


def test_host_sync_in_jitted_function():
    assert 'SKY101' in codes("""
        import jax

        @jax.jit
        def step(x):
            return int(x)
    """)


def test_host_sync_in_jit_call_target():
    # jax.jit(f) marks f traced even without a decorator.
    assert 'SKY101' in codes("""
        import jax

        def step(x):
            return x.item()

        step_fn = jax.jit(step)
    """)


def test_host_sync_in_fori_loop_body():
    assert 'SKY101' in codes("""
        from jax import lax
        import numpy as np

        def run(x):
            def body(i, carry):
                return np.asarray(carry)
            return lax.fori_loop(0, 4, body, x)

        import jax
        run_fn = jax.jit(run)
    """)


def test_untraced_function_is_clean():
    # Host code may int()/np.asarray() freely.
    assert codes("""
        import numpy as np

        def host_side(x):
            return int(np.asarray(x))
    """, path='jobs/host.py') == []


def test_tracer_control_flow():
    assert 'SKY102' in codes("""
        import jax

        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
    """)


def test_static_control_flow_is_clean():
    # kwonly params are static (repo convention: partial + static_argnames)
    # and `is None` / isinstance tests never concretize a tracer.
    assert codes("""
        import jax

        @jax.jit
        def step(x, *, n):
            if n > 2:
                x = x + 1
            if x is None:
                return 0
            if isinstance(x, dict):
                return x['a']
            return x
    """) == []


def test_impure_and_prng_in_jit():
    got = codes("""
        import jax, time

        @jax.jit
        def step(x):
            time.monotonic()
            print(x)
            key = jax.random.PRNGKey(0)
            return x
    """)
    assert got.count('SKY103') == 2 and 'SKY104' in got


def test_f64_promotion():
    got = codes("""
        import numpy as np

        def make(x):
            a = np.zeros(3, dtype='float64')
            b = np.float64(x)
            return a, b
    """)
    assert got.count('SKY106') == 2


def test_host_fetch_bypass_only_in_data_plane():
    bad = """
        import numpy as np

        def drain(x):
            return np.asarray(x)
    """
    assert 'SKY105' in codes(bad, path='infer/serving.py')
    # Same code outside the decode data plane is fine...
    assert 'SKY105' not in codes(bad, path='jobs/core.py')
    # ...and host_fetch itself is THE sanctioned transfer point.
    assert 'SKY105' not in codes("""
        import numpy as np

        def host_fetch(*arrays):
            return tuple(np.asarray(a) for a in arrays)
    """, path='infer/engine.py')


def test_blocking_in_async_handler():
    got = codes("""
        import time, requests

        async def handler(request):
            time.sleep(1)
            return requests.get('http://replica')
    """, path='serve/load_balancer.py')
    assert got.count('SKY201') == 2


def test_sleep_poll_loop_and_backoff_allowlist():
    bad = """
        import time

        def wait(pred):
            while not pred():
                time.sleep(0.2)
    """
    assert 'SKY202' in codes(bad, path='jobs/core.py')
    # The bounded-backoff helper is the sanctioned home for this sleep.
    assert 'SKY202' not in codes(bad, path='utils/backoff.py')


def test_silent_except_only_on_recovery_paths():
    bad = """
        def recover():
            try:
                relaunch()
            except ValueError:
                pass
    """
    assert 'SKY302' in codes(bad, path='jobs/pool.py')
    assert 'SKY302' not in codes(bad, path='infer/engine.py')
    assert codes("""
        def recover():
            try:
                relaunch()
            except:
                raise SystemExit
    """, path='infer/engine.py') == ['SKY301']


def test_unbounded_recovery_loop_flagged():
    """SKY303: a retry-forever recovery loop — while True around a
    recover call whose except swallows the failure — on a jobs/serve
    path is a finding; the same loop with a Backoff/attempt bound (or
    off the recovery paths) is sanctioned."""
    bad = """
        def run(strategy):
            while True:
                try:
                    strategy.recover()
                except Exception:
                    continue
    """
    assert 'SKY303' in codes(bad, path='jobs/controller.py')
    assert 'SKY303' in codes(bad, path='serve/autoscaler.py')
    # Not a recovery path: the rule stays quiet.
    assert 'SKY303' not in codes(bad, path='infer/engine.py')
    # A loop with no exit at all around a launch call is the same bug.
    assert 'SKY303' in codes("""
        def run(strategy):
            while True:
                strategy.launch()
    """, path='jobs/controller.py')


def test_bounded_recovery_loop_is_clean():
    # Backoff-driven retries (the sanctioned shape) pass.
    assert 'SKY303' not in codes("""
        from skypilot_tpu.utils.backoff import Backoff

        def run(strategy):
            backoff = Backoff(initial=1.0, cap=30.0)
            while True:
                try:
                    strategy.recover()
                except Exception:
                    backoff.sleep()
    """, path='jobs/controller.py')
    # An explicit attempt bound passes.
    assert 'SKY303' not in codes("""
        def run(strategy, max_recovery_attempts):
            for attempt in range(max_recovery_attempts):
                try:
                    return strategy.recover()
                except Exception:
                    continue
    """, path='jobs/controller.py')
    # A monitor loop that RETURNS on outcomes is not a retry loop.
    assert 'SKY303' not in codes("""
        def monitor(strategy):
            while True:
                try:
                    status = strategy.recover()
                except Exception:
                    return None
                if status is not None:
                    return status
    """, path='jobs/controller.py')


def test_replica_removal_without_cleanup():
    """SKY304: dropping a replica from a membership collection on a
    jobs/serve path without touching ring/health/breaker state in the
    same function leaves the hashring routing at a dead URL."""
    bad = """
        class Fleet:
            def kill(self, rep):
                self.replicas.remove(rep)
    """
    assert 'SKY304' in codes(bad, path='serve/manager.py')
    assert 'SKY304' in codes(bad, path='jobs/pool.py')
    # Off the recovery paths: the rule stays quiet.
    assert 'SKY304' not in codes(bad, path='infer/engine.py')
    # pop / del forms are the same bug.
    assert 'SKY304' in codes("""
        def evict(replica_map, url):
            replica_map.pop(url)
    """, path='serve/manager.py')
    assert 'SKY304' in codes("""
        def evict(replicas_by_url, url):
            del replicas_by_url[url]
    """, path='serve/manager.py')


def test_replica_removal_with_cleanup_is_clean():
    # Ring/health/breaker teardown in the same function sanctions it.
    assert 'SKY304' not in codes("""
        class Fleet:
            def kill(self, rep):
                self.replicas.remove(rep)
                self.ring.remove_member(rep.url)
                self.breaker.forget(rep.url)
    """, path='serve/manager.py')
    # Delegating to the policy-sync helper counts too.
    assert 'SKY304' not in codes("""
        class Fleet:
            def kill(self, rep):
                self.replicas.remove(rep)
                self._sync_policy()
    """, path='serve/manager.py')
    # Collections that aren't replica membership are not the rule's
    # business; cleanup inside a nested def is its own scope and
    # does NOT sanction the outer removal.
    assert 'SKY304' not in codes("""
        def trim(queue):
            queue.pop(0)
    """, path='serve/manager.py')
    assert 'SKY304' in codes("""
        class Fleet:
            def kill(self, rep):
                self.replicas.remove(rep)
                def later():
                    self.ring.remove_member(rep.url)
    """, path='serve/manager.py')
    # The explicit allow marker works for sanctioned sites.
    assert 'SKY304' not in codes("""
        class Fleet:
            def kill(self, rep):
                self.replicas.remove(rep)  # skytpu-allow: SKY304
    """, path='serve/manager.py')


def test_metric_family_outside_registry():
    """SKY401: a Prometheus family constructed anywhere but the
    shared-registry modules — dotted prometheus_client form, or a bare
    name with a registry= kwarg."""
    assert 'SKY401' in codes("""
        import prometheus_client

        REQS = prometheus_client.Counter('skytpu_lb_requests_total',
                                         'requests')
    """, path='serve/load_balancer.py')
    assert 'SKY401' in codes("""
        from prometheus_client import Gauge
        from skypilot_tpu.metrics import REGISTRY

        DEPTH = Gauge('skytpu_lb_queue_depth', 'depth',
                      registry=REGISTRY)
    """, path='serve/load_balancer.py')
    # The registry modules are the sanctioned homes.
    sanctioned = """
        from prometheus_client import Histogram
        from skypilot_tpu.metrics import REGISTRY

        H = Histogram('skytpu_x_seconds', 'x', registry=REGISTRY)
    """
    assert 'SKY401' not in codes(sanctioned, path='telemetry/metrics.py')
    assert 'SKY401' not in codes(sanctioned, path='metrics/utils.py')
    # The allow marker sanctions a one-off site.
    assert 'SKY401' not in codes("""
        from prometheus_client import Gauge

        G = Gauge('skytpu_y', 'y', registry=None)  # skytpu-allow: SKY401
    """, path='serve/load_balancer.py')


def test_metric_family_rule_ignores_collections_counter():
    # collections.Counter / a bare Counter without registry= are the
    # stdlib multiset, not a metric family — never flagged.
    assert 'SKY401' not in codes("""
        import collections

        def tally(xs):
            return collections.Counter(xs)
    """, path='serve/spot_placer.py')
    assert 'SKY401' not in codes("""
        from collections import Counter

        def tally(xs):
            return Counter(xs)
    """, path='analysis/baseline.py')


def test_wall_clock_in_data_plane():
    """SKY402: direct time.time()/time.monotonic() in a serving
    data-plane module — these classes take injectable clocks so
    virtual-time (simulator) runs stay deterministic."""
    bad = """
        import time

        def stamp(span):
            span['t0'] = time.time()
            span['mono'] = time.monotonic()
    """
    assert codes(bad, path='serve/load_balancer.py').count('SKY402') == 2
    assert 'SKY402' in codes(bad, path='telemetry/spans.py')
    assert 'SKY402' in codes(bad, path='infer/serving.py')
    # Outside the data plane the wall clock is nobody's business.
    assert 'SKY402' not in codes(bad, path='jobs/core.py')
    assert 'SKY402' not in codes(bad, path='infer/engine.py')


def test_wall_clock_sanctioned_patterns_are_clean():
    # Injectable-clock reads and perf_counter (duration-only, never
    # compared across processes) are the sanctioned shapes; a default
    # expression that merely REFERENCES time.time without calling it
    # is fine too.
    assert 'SKY402' not in codes("""
        import time

        class LB:
            def __init__(self, clock=None):
                self._clock = clock or time.time

            def now(self):
                return self._clock()

        def span_len(t0):
            return time.perf_counter() - t0
    """, path='serve/load_balancer.py')
    # The allow marker sanctions a one-off site (e.g. a db timestamp).
    assert 'SKY402' not in codes("""
        import time

        def stamp():
            return time.time()  # skytpu-allow: SKY402
    """, path='serve/serve_state.py')


def test_inline_allow_suppresses():
    assert codes("""
        import jax

        @jax.jit
        def step(x):
            return int(x)  # skytpu-allow: SKY101
    """) == []


def test_parse_error_is_a_finding():
    assert codes('def broken(:\n') == ['SKY000']


# ---------------------------------------------------------------------------
# 2. Package gate + baseline discipline
# ---------------------------------------------------------------------------


def test_package_lints_clean_against_baseline():
    violations = linter.lint_paths([PACKAGE_ROOT], root=REPO_ROOT)
    baseline = baseline_lib.load_baseline()
    new, _, _ = baseline_lib.diff_baseline(violations, baseline)
    assert not new, ('NEW lint violations (fix them or, if sanctioned, '
                     'mark "# skytpu-allow: <code>"):\n'
                     + '\n'.join(v.format() for v in new))


def test_baseline_must_not_grow():
    # The suppression set may shrink (prune stale entries with
    # --update-baseline after fixing) but NEVER grow: new violations
    # must be fixed or inline-allowed, not baselined away.
    assert len(baseline_lib.load_baseline()) <= 5


def test_baseline_fingerprint_survives_line_drift():
    src = 'import time\n\ndef f():\n    while True:\n        time.sleep(1)\n'
    shifted = '# a new header comment\n' + src
    (fp1, _), = baseline_lib.fingerprint_violations(
        linter.lint_source(src, 'jobs/x.py'))
    (fp2, _), = baseline_lib.fingerprint_violations(
        linter.lint_source(shifted, 'jobs/x.py'))
    assert fp1 == fp2


# ---------------------------------------------------------------------------
# 3. Auditor: budgets hold, and the negative directions really fail
# ---------------------------------------------------------------------------


@pytest.fixture(scope='module')
def audit_lib():
    from skypilot_tpu.analysis import audit
    return audit


def test_decode_compiles_once_per_bucket_and_donates(audit_lib):
    report = audit_lib.audit_generator_decode()
    by_name = {c['name']: c for c in report['checks']}
    # Exactly one compile per cache bucket for a bucket-crossing run —
    # not merely <= budget: fewer would mean the run didn't cross.
    assert report['compiles'] == len(report['buckets'])
    assert by_name['compile_per_bucket']['status'] == 'ok'
    assert by_name['donation']['status'] == 'ok', \
        by_name['donation']['detail']
    assert by_name['no_callbacks']['status'] == 'ok'
    assert by_name['no_f64']['status'] == 'ok'


def test_audit_run_is_green(audit_lib):
    report = audit_lib.run_audit()
    assert report['ok'], [
        (e['entry'], c) for e in report['entries']
        for c in e['checks'] if c['status'] == 'fail']


def test_extra_recompile_breaches_budget(audit_lib):
    # Simulate a retrace regression: warm the jit cache with stray
    # static n values before the audited run.  Two strays, because the
    # pooled budget allows 2 programs total (full chunk + context-
    # ceiling tail) and the audited run itself only exercises one — a
    # regression that retraces per step blows past it either way.
    gen = audit_lib.make_tiny_generator()
    for stray_n in (3, 5):
        args, _ = audit_lib._decode_chunk_inputs(
            gen, gen.cache_buckets[0], stray_n)
        gen._decode_chunk(*args, n=stray_n)
    report = audit_lib.audit_generator_decode(gen)
    by_name = {c['name']: c for c in report['checks']}
    assert by_name['compile_per_bucket']['status'] == 'fail'


def test_int_tracer_fails_audit(audit_lib, monkeypatch):
    # A synthetic int(tracer) in the decode chunk: tracing raises
    # ConcretizationTypeError, which the auditor reports as a failed
    # check rather than crashing.
    import functools

    import jax as jax_lib

    real_make = audit_lib.make_tiny_generator

    def make_broken():
        gen = real_make()
        real_impl = gen._decode_chunk_impl

        def bad_impl(params, token, cache, positions, done, limit, rng,
                     tables=None, *, n, temperature, top_k, top_p, eos):
            int(token[0])  # the defect under test
            return real_impl(params, token, cache, positions, done,
                             limit, rng, tables, n=n,
                             temperature=temperature,
                             top_k=top_k, top_p=top_p, eos=eos)

        gen._decode_chunk = jax_lib.jit(
            functools.partial(bad_impl, temperature=gen.gen.temperature,
                              top_k=gen.gen.top_k, top_p=gen.gen.top_p,
                              eos=gen.gen.eos_token),
            donate_argnums=(2,), static_argnames=('n',))
        return gen

    monkeypatch.setattr(audit_lib, 'make_tiny_generator', make_broken)
    report = audit_lib.run_audit(entries=['generator_decode'])
    assert not report['ok']
    (entry,) = report['entries']
    fails = [c for c in entry['checks'] if c['status'] == 'fail']
    assert fails and 'ConcretizationTypeError' in fails[0]['detail']


def test_int_tracer_in_decode_source_is_lint_caught():
    # The static half of the same defect: inject `int(token[0])` into
    # the real engine source's decode-chunk body and lint it.
    path = os.path.join(PACKAGE_ROOT, 'infer', 'engine.py')
    with open(path, 'r', encoding='utf-8') as f:
        lines = f.read().splitlines(keepends=True)
    assert not [v for v in linter.lint_source(''.join(lines),
                                              'infer/engine.py')
                if v.code == 'SKY101'], 'engine.py must start clean'
    anchor = next(i for i, ln in enumerate(lines)
                  if 'def _decode_chunk_impl' in ln)
    # Signature spans lines until the one ending in ':'.
    body_at = next(i for i in range(anchor, len(lines))
                   if lines[i].rstrip().endswith(':')) + 1
    injected = ''.join(lines[:body_at]
                       + ['        _bad = int(token[0])\n']
                       + lines[body_at:])
    got = [v for v in linter.lint_source(injected, 'infer/engine.py')
           if v.code == 'SKY101']
    assert got, 'injected int(tracer) in decode chunk must be flagged'


def test_quick_summary_shape(audit_lib):
    summary = audit_lib.quick_summary()
    assert summary['compile_budget_ok'] and summary['cache_donated']
    assert summary['failures'] == 0
    assert summary['decode_compiles'] == len(summary['cache_buckets'])
    assert summary['lint_rules'] == len(linter.RULES)
    assert summary['graph_thread_entries'] > 0


def test_cli_json_contract():
    import json
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.analysis', '--json'],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report['ok'] and report['new'] == []


# ---------------------------------------------------------------------------
# 4. Whole-program call graph + SKY5xx concurrency rules
# ---------------------------------------------------------------------------


def codes_multi(sources):
    return [v.code for v in linter.lint_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})]


def sky5xx(sources):
    return [c for c in codes_multi(sources) if c.startswith('SKY5')]


def test_sky501_unlocked_cross_thread_counter():
    # The injected defect: a counter bumped on the worker thread and read
    # from the owner with no common lock.  Exactly SKY501, nothing else.
    assert sky5xx({'pkg/pump.py': """
        import threading

        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self.count = 0

            def start(self):
                self._thread.start()

            def stop(self):
                self._thread.join()

            def _run(self):
                for _ in range(100):
                    self.count += 1

            def read(self):
                return self.count
    """}) == ['SKY501']


def test_sky501_clean_when_both_planes_lock():
    assert sky5xx({'pkg/pump.py': """
        import threading

        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                self._thread.start()

            def stop(self):
                self._thread.join()

            def _run(self):
                for _ in range(100):
                    with self._lock:
                        self.count += 1

            def read(self):
                with self._lock:
                    return self.count
    """}) == []


def test_sky502_two_lock_ordering_cycle():
    assert sky5xx({'pkg/pair.py': """
        import threading

        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def forward(self):
                with self.a:
                    with self.b:
                        return 1

            def backward(self):
                with self.b:
                    with self.a:
                        return 2
    """}) == ['SKY502']


def test_sky502_consistent_order_is_clean():
    assert sky5xx({'pkg/pair.py': """
        import threading

        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def forward(self):
                with self.a:
                    with self.b:
                        return 1

            def backward(self):
                with self.a:
                    with self.b:
                        return 2
    """}) == []


def test_sky503_unjoined_daemon_thread():
    assert sky5xx({'pkg/poller.py': """
        import threading

        class Poller:
            def __init__(self):
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                return None
    """}) == ['SKY503']


def test_sky503_joined_thread_is_clean():
    assert sky5xx({'pkg/poller.py': """
        import threading

        class Poller:
            def __init__(self):
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def stop(self):
                if self._thread is not None:
                    self._thread.join(timeout=5)

            def _run(self):
                return None
    """}) == []


def test_sky504_blocking_get_on_step_path():
    # queue.get() with no timeout, reachable from the batcher hot path
    # through an intermediate helper — only the call graph sees this.
    assert sky5xx({'infer/serving.py': """
        import queue

        class ContinuousBatcher:
            def __init__(self):
                self._q = queue.Queue()

            def step(self):
                return self._pull()

            def _pull(self):
                return self._q.get()
    """}) == ['SKY504']


def test_sky504_timeout_get_is_clean():
    assert sky5xx({'infer/serving.py': """
        import queue

        class ContinuousBatcher:
            def __init__(self):
                self._q = queue.Queue()

            def step(self):
                return self._pull()

            def _pull(self):
                try:
                    return self._q.get(timeout=0.1)
                except queue.Empty:
                    return None
    """}) == []


def test_traced_discovery_follows_indirect_calls():
    # `inner` is never decorated; only the call edge from the jitted
    # `outer` marks it traced.  The legacy per-module heuristic misses
    # this entirely.
    multi = codes_multi({'infer/model.py': """
        import jax

        def inner(x):
            print(x)
            return x

        @jax.jit
        def outer(x):
            return inner(x)
    """})
    assert 'SKY103' in multi
    assert [] == [c for c in codes("""
        import jax

        def inner(x):
            print(x)
            return x

        @jax.jit
        def outer(x):
            return inner(x)
    """, path='infer/model.py') if c == 'SKY103']


def test_traced_discovery_resolves_methods():
    # x.item() in a helper reached only through self-method resolution.
    assert 'SKY101' in codes_multi({'infer/model.py': """
        import jax

        class Decoder:
            def _helper(self, x):
                return x.item()

            @jax.jit
            def run(self, x):
                return self._helper(x)
    """})


def test_dead_code_not_treated_as_traced():
    # `orphan` has a jitty name shape but no decorator and no call edge
    # from any traced root: the graph re-base must NOT flag its print.
    assert codes_multi({'infer/model.py': """
        def orphan_step_fn(x):
            print(x)
            return x
    """}) == []


def test_graph_thread_target_edge():
    from skypilot_tpu.analysis import graph as graph_lib
    g = graph_lib.build_graph({'pkg/w.py': textwrap.dedent("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                return None
    """)})
    assert 'pkg/w.py::Worker._run' in g.thread_entries
    assert any(t[1] == 'pkg/w.py::Worker._run' and t[2] == 'thread'
               for t in g.thread_edges)


def test_graph_submit_edge():
    from skypilot_tpu.analysis import graph as graph_lib
    g = graph_lib.build_graph({'pkg/w.py': textwrap.dedent("""
        from concurrent.futures import ThreadPoolExecutor

        class Worker:
            def __init__(self):
                self._pool = ThreadPoolExecutor(2)

            def kick(self):
                return self._pool.submit(self._work, 1)

            def _work(self, n):
                return n
    """)})
    assert 'pkg/w.py::Worker._work' in g.thread_entries


def test_graph_method_resolution_is_class_scoped():
    from skypilot_tpu.analysis import graph as graph_lib
    g = graph_lib.build_graph({'pkg/m.py': textwrap.dedent("""
        class A:
            def go(self):
                return self.helper()

            def helper(self):
                return 1

        class B:
            def helper(self):
                return 2
    """)})
    edges = g.call_edges.get('pkg/m.py::A.go', set())
    assert 'pkg/m.py::A.helper' in edges
    assert 'pkg/m.py::B.helper' not in edges


def test_package_graph_stats_are_nonzero():
    from skypilot_tpu.analysis import graph as graph_lib
    stats = graph_lib.build_package_graph(REPO_ROOT).stats()
    assert stats['files'] > 100
    assert stats['functions'] > 1000
    assert stats['call_edges'] > 1000
    assert stats['thread_entries'] > 5


def test_sky1xx_graph_rebase_only_shrinks_findings():
    # Drift gate for the call-graph re-base: on the current tree the new
    # pipeline may only REMOVE SKY101-105 findings relative to the legacy
    # per-module heuristic (dead code pruned), never add them.
    from skypilot_tpu.analysis import graph as graph_lib
    sources = graph_lib.package_sources(REPO_ROOT)
    tracked = ('SKY101', 'SKY102', 'SKY103', 'SKY104', 'SKY105')
    old = {(v.path, v.line, v.code)
           for path, src in sources.items()
           for v in linter.lint_source(src, path)
           if v.code in tracked}
    new = {(v.path, v.line, v.code)
           for v in linter.lint_sources(sources)
           if v.code in tracked}
    assert new <= old, f'graph re-base ADDED findings: {new - old}'


def test_unused_suppression_is_reported():
    multi = codes_multi({'pkg/x.py': """
        def f():
            return 1  # skytpu-allow: SKY101
    """})
    assert multi == ['SKY601']


def test_used_suppression_not_reported():
    assert codes_multi({'infer/x.py': """
        import jax

        @jax.jit
        def step(x):
            return int(x)  # skytpu-allow: SKY101
    """}) == []


def test_allow_marker_in_docstring_is_not_a_suppression():
    # Only real comments count: a docstring MENTIONING the marker is
    # neither a suppression nor a stale one.
    assert codes_multi({'pkg/x.py': '''
        def f():
            """Docs about # skytpu-allow: SKY101 markers."""
            return 1
    '''}) == []
