"""Storage subsystem: store classes, mount-command builders, modes,
state tracking + CLI (reference: sky/data/storage.py StoreType/
StorageMode/Storage, sky/data/mounting_utils.py)."""
import os

import pytest

from skypilot_tpu.data import mounting_utils
from skypilot_tpu.data.storage import (Storage, StorageMode, StoreType,
                                       delete_storage, list_storage)


def test_store_uris():
    assert Storage('b', store=StoreType.GCS).uri() == 'gs://b'
    assert Storage('b', store=StoreType.S3).uri() == 's3://b'
    assert Storage('b', store=StoreType.R2).uri() == 'r2://b'
    azure = Storage('b', store=StoreType.AZURE,
                    store_config={'storage_account': 'acc'})
    assert azure.uri() == 'https://acc.blob.core.windows.net/b'


def test_mount_commands_per_store():
    gcs = Storage('bkt', store=StoreType.GCS)
    assert 'gcsfuse' in gcs.mount_command('/data')
    s3 = Storage('bkt', store=StoreType.S3)
    assert 'goofys' in s3.mount_command('/data')
    r2 = Storage('bkt', store=StoreType.R2,
                 store_config={'account_id': 'acct123'})
    assert 'https://acct123.r2.cloudflarestorage.com' in \
        r2.mount_command('/data')
    az = Storage('bkt', store=StoreType.AZURE,
                 store_config={'storage_account': 'acc'})
    assert 'blobfuse2' in az.mount_command('/data')


def test_mount_modes_change_command():
    copy = Storage('bkt', store=StoreType.GCS, mode=StorageMode.COPY)
    assert 'gsutil -m rsync' in copy.mount_command('/data')
    cached = Storage('bkt', store=StoreType.GCS,
                     mode=StorageMode.MOUNT_CACHED)
    assert 'file-cache-max-size-mb' in cached.mount_command('/data')
    s3_cached = Storage('bkt', store=StoreType.S3,
                        mode=StorageMode.MOUNT_CACHED)
    assert 'rclone mount' in s3_cached.mount_command('/data')
    assert 'vfs-cache-mode writes' in s3_cached.mount_command('/data')


def test_mount_commands_are_idempotent():
    """Every FUSE mount guards with mountpoint -q (re-running setup on a
    host must not double-mount)."""
    for store, cfg in ((StoreType.GCS, None), (StoreType.S3, None),
                      (StoreType.R2, {'account_id': 'acct'}),
                      (StoreType.AZURE, {'storage_account': 'a'})):
        cmd = Storage('b', store=store,
                      store_config=cfg).mount_command('/data')
        assert 'mountpoint -q' in cmd, store


def test_r2_requires_account_and_copies_via_r2_endpoint():
    from skypilot_tpu import exceptions
    r2 = Storage('bkt', store=StoreType.R2,
                 store_config={'account_id': 'acct'},
                 mode=StorageMode.COPY)
    cmd = r2.mount_command('/data')
    assert 'acct.r2.cloudflarestorage.com' in cmd  # never plain AWS
    with pytest.raises(exceptions.StorageSpecError):
        Storage('bkt', store=StoreType.R2).mount_command('/data')


def test_azure_cli_targets_configured_account():
    az = Storage('bkt', store=StoreType.AZURE,
                 store_config={'storage_account': 'acc'})
    # uri + mount both resolve through the configured account; missing
    # account is a spec error, not a silent default.
    assert 'acc.blob.core.windows.net' in az.uri()
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.StorageSpecError):
        Storage('bkt', store=StoreType.AZURE).uri()


def test_s3_cached_mount_uses_connection_string():
    cmd = Storage('bkt', store=StoreType.S3,
                  mode=StorageMode.MOUNT_CACHED).mount_command('/d')
    # A named remote would need a pre-seeded rclone.conf on the host.
    assert ':s3,env_auth=true:bkt' in cmd


def test_delete_storage_uses_persisted_config(tmp_home, monkeypatch):
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.data import storage as storage_lib
    calls = {}
    monkeypatch.setattr(
        storage_lib.R2Store, 'delete',
        lambda self: calls.setdefault('config', self.config))
    state_lib.add_storage('r2b', 'r2', 'MOUNT', None,
                          config={'account_id': 'acct9'})
    storage_lib.delete_storage('r2b')
    assert calls['config'] == {'account_id': 'acct9'}
    assert state_lib.get_storage('r2b') is None


def test_copy_download_command_dispatch():
    assert 'gsutil' in mounting_utils.copy_download_command('gs://b', '/d')
    assert 'aws s3 sync' in mounting_utils.copy_download_command(
        's3://b', '/d')
    assert 'azcopy' in mounting_utils.copy_download_command(
        'https://a.blob.core.windows.net/b', '/d')


def test_local_store_end_to_end(tmp_home, tmp_path):
    src = tmp_path / 'src'
    src.mkdir()
    (src / 'weights.bin').write_text('w')
    storage = Storage('ckpt', source=str(src), store=StoreType.LOCAL)
    storage.create_if_missing()
    storage.sync_source()
    assert os.path.exists(os.path.join(storage.uri(), 'weights.bin'))
    storage.delete()
    assert not os.path.exists(storage.uri())


def test_storage_mount_via_local_launch(tmp_home, tmp_path):
    """Full path: task file_mounts dict -> bucket synced -> mounted on the
    local cluster -> tracked in state -> delete removes both."""
    import skypilot_tpu as sky
    src = tmp_path / 'data'
    src.mkdir()
    (src / 'input.txt').write_text('payload')
    mnt = str(tmp_path / 'mnt')
    task = sky.Task(
        run=f'cat {mnt}/input.txt', name='t',
        file_mounts={mnt: {
            'name': 'mnt-bkt', 'store': 'local', 'source': str(src)}})
    task.set_resources(sky.Resources(cloud='local'))
    sky.launch(task, cluster_name='st')
    try:
        rows = list_storage()
        assert [r['name'] for r in rows] == ['mnt-bkt']
        assert rows[0]['last_attached_cluster'] == 'st'
    finally:
        sky.down('st')
    delete_storage('mnt-bkt')
    assert list_storage() == []


def test_tilde_mount_target_expands_on_host(tmp_home, tmp_path):
    """`file_mounts: {~/mnt: {...}}` must expand ~ on the HOST (quoting
    it literally broke every tilde mount)."""
    import skypilot_tpu as sky
    from skypilot_tpu.data import mounting_utils
    assert mounting_utils.quote_path('~/mnt') == '"$HOME"/mnt'
    assert mounting_utils.quote_path('/abs path') == "'/abs path'"
    src = tmp_path / 'd'
    src.mkdir()
    (src / 'in.txt').write_text('tilde-ok')
    task = sky.Task(
        run='cat ~/mnt/in.txt', name='t',
        file_mounts={'~/mnt': {
            'name': 'tilde-bkt', 'store': 'local', 'source': str(src)}})
    task.set_resources(sky.Resources(cloud='local'))
    sky.launch(task, cluster_name='tl')
    try:
        import os as os_lib
        assert os_lib.path.exists(
            os_lib.path.expanduser('~/mnt/in.txt'))
    finally:
        sky.down('tl')
        delete_storage('tilde-bkt')


def test_storage_cli(tmp_home, capsys):
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.client import cli
    state_lib.add_storage('bkt1', 'gcs', 'MOUNT', 'c1')
    assert cli.main(['storage', 'ls']) == 0
    out = capsys.readouterr().out
    assert 'bkt1' in out and 'gcs' in out
    # CLI delete of a local-store bucket removes tracking.
    state_lib.add_storage('bkt2', 'local', 'MOUNT', None)
    assert cli.main(['storage', 'delete', 'bkt2']) == 0
    names = [r['name'] for r in state_lib.list_storage()]
    assert 'bkt2' not in names


def test_unknown_store_rejected():
    from skypilot_tpu import exceptions
    with pytest.raises(ValueError):
        Storage.from_yaml_config({'name': 'b', 'store': 'floppy'})
    with pytest.raises(exceptions.StorageSpecError):
        Storage.from_yaml_config({'store': 'gcs'})
