import textwrap

import pytest

from skypilot_tpu import Dag, Resources, Task
from skypilot_tpu import exceptions


def test_minimal_task():
    t = Task(name='t1', run='echo hello')
    assert t.num_nodes == 1
    assert t.generate_run_command(0, ['127.0.0.1']) == 'echo hello'


def test_run_callable_per_rank():
    t = Task(run=lambda rank, ips: f'echo rank {rank} of {len(ips)}')
    assert t.generate_run_command(1, ['a', 'b']) == 'echo rank 1 of 2'


def test_invalid_name():
    with pytest.raises(exceptions.InvalidTaskError):
        Task(name='bad name!')


def test_env_overlap_with_secrets():
    with pytest.raises(exceptions.InvalidTaskError):
        Task(envs={'A': '1'}, secrets={'A': '2'})


def test_from_yaml_config(tmp_path):
    yaml_str = textwrap.dedent("""\
        name: train
        num_nodes: 1
        resources:
          accelerators: tpu-v5e-16
          use_spot: true
        envs:
          MODEL: llama3-8b
        setup: pip list
        run: |
          python train.py --model ${MODEL}
    """)
    p = tmp_path / 'task.yaml'
    p.write_text(yaml_str)
    t = Task.from_yaml(str(p))
    assert t.name == 'train'
    assert t.best_resources.accelerator_name == 'tpu-v5e-16'
    assert t.best_resources.use_spot
    # ${MODEL} expanded from envs
    assert 'llama3-8b' in t.generate_run_command(0, ['x'])


def test_yaml_roundtrip():
    t = Task(name='rt', run='echo hi', envs={'A': '1'}, num_nodes=2)
    t.set_resources(Resources(accelerators='tpu-v4-16'))
    cfg = t.to_yaml_config()
    t2 = Task.from_yaml_config(cfg)
    assert t2.name == 'rt'
    assert t2.num_nodes == 2
    assert t2.best_resources.accelerator_name == 'tpu-v4-16'


def test_unknown_key_rejected():
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({'nme': 'typo', 'run': 'x'})


def test_dag_auto_registration():
    with Dag('pipeline') as dag:
        a = Task(name='a', run='echo a')
        b = Task(name='b', run='echo b')
        dag.add_edge(a, b)
    assert dag.tasks == [a, b]
    assert dag.is_chain()


def test_dag_cycle_rejected():
    dag = Dag()
    a = Task(name='a')
    b = Task(name='b')
    dag.add_edge(a, b)
    with pytest.raises(exceptions.InvalidTaskError):
        dag.add_edge(b, a)
