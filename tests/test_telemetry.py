"""End-to-end telemetry layer (skypilot_tpu/telemetry/): data-plane
metric families on the shared registry, trace-context propagation
(server -> executor -> agent), nested timeline spans sharing one trace
file across processes, and JSONL step-telemetry."""
import json
import os
import time

import pytest

from skypilot_tpu.metrics import REGISTRY
from skypilot_tpu.telemetry import metrics as telemetry_metrics
from skypilot_tpu.telemetry import steplog
from skypilot_tpu.telemetry import trace as trace_lib
from skypilot_tpu.utils import timeline
from tests.test_api_server import live_server  # noqa: F401  (fixture)
from tests.test_launch_e2e import iso_state  # noqa: F401  (fixture)


def _sample(name, labels=None):
    return REGISTRY.get_sample_value(name, labels or {})


# --- metric families / naming contract ---

def test_all_families_use_skytpu_prefix():
    """Every family on the shared registry carries the skytpu_ prefix —
    the exposition contract scrape configs and dashboards rely on."""
    for family in REGISTRY.collect():
        assert family.name.startswith('skytpu_'), family.name


def test_render_metrics_exposes_data_plane_families():
    from skypilot_tpu import metrics as metrics_lib
    text = metrics_lib.render_metrics().decode('utf-8')
    families = {line.split()[2] for line in text.splitlines()
                if line.startswith('# TYPE ')}
    data_plane = {f for f in families
                  if f.startswith(('skytpu_train_', 'skytpu_infer_',
                                   'skytpu_serve_'))}
    assert len(data_plane) >= 8, sorted(data_plane)


def test_histogram_quantile():
    for v in (0.01, 0.02, 0.02, 0.2):
        telemetry_metrics.INFER_DECODE_CHUNK_SECONDS.observe(v)
    q = telemetry_metrics.histogram_quantile(
        telemetry_metrics.INFER_DECODE_CHUNK_SECONDS, 0.5)
    assert q is not None and 0.0 < q <= 0.25


# --- data-plane emission from a real (tiny, CPU) train/infer run ---

@pytest.mark.slow
def test_trainer_fit_populates_train_metrics():
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import MeshConfig, make_mesh
    from skypilot_tpu.parallel import sharding as sharding_lib
    from skypilot_tpu.train import TrainConfig, Trainer, synthetic_batches
    config = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                               n_heads=4, n_kv_heads=2, d_ff=128,
                               max_seq_len=64, dtype=jnp.float32,
                               remat=False)
    mesh = make_mesh(MeshConfig(fsdp=len(jax.devices())))
    params = llama.init_params(config, jax.random.PRNGKey(0))
    trainer = Trainer(lambda p, b: llama.loss_fn(p, b, config), params,
                      mesh, sharding_lib.LLAMA_RULES,
                      TrainConfig(warmup_steps=1, total_steps=3))

    def count(phase):
        return _sample('skytpu_train_step_duration_seconds_count',
                       {'phase': phase}) or 0.0

    warmup0, steady0 = count('warmup'), count('steady')
    steps0 = _sample('skytpu_train_steps_total') or 0.0
    summary = trainer.fit(synthetic_batches(8, 32, config.vocab_size), 3,
                          log_every=0, tokens_per_batch=8 * 32,
                          flops_per_token=6 * config.num_params())
    assert count('warmup') == warmup0 + 1
    assert count('steady') == steady0 + 2
    assert (_sample('skytpu_train_steps_total') or 0.0) == steps0 + 3
    assert _sample('skytpu_train_tokens_per_second') == pytest.approx(
        summary['tokens_per_sec'])
    assert _sample('skytpu_train_loss') == pytest.approx(summary['loss'])
    assert summary['mfu'] > 0
    assert _sample('skytpu_train_mfu_ratio') == pytest.approx(
        summary['mfu'])


@pytest.mark.slow
def test_generator_generate_populates_infer_metrics():
    import jax
    from skypilot_tpu.infer import Generator, GeneratorConfig
    from skypilot_tpu.models import llama
    config = llama.LLAMA_DEBUG
    params = llama.init_params(config, jax.random.PRNGKey(0))
    gen = Generator(params, config,
                    GeneratorConfig(max_seq_len=64, batch_size=2,
                                    prompt_buckets=[16]))
    prefill0 = _sample('skytpu_infer_prefill_duration_seconds_count',
                       {'bucket': '16'}) or 0.0
    tokens0 = _sample('skytpu_infer_generated_tokens_total') or 0.0
    out = gen.generate([[5, 9, 2, 7], [11, 3]], max_new_tokens=8)
    assert _sample('skytpu_infer_prefill_duration_seconds_count',
                   {'bucket': '16'}) == prefill0 + 1
    generated = sum(len(o) for o in out)
    assert _sample('skytpu_infer_generated_tokens_total') == \
        tokens0 + generated
    assert (_sample('skytpu_infer_steady_tokens_per_second') or 0.0) > 0


@pytest.mark.slow
def test_batcher_populates_queue_and_occupancy_metrics():
    import jax
    from skypilot_tpu.infer import GeneratorConfig
    from skypilot_tpu.infer.serving import ContinuousBatcher
    from skypilot_tpu.models import llama
    config = llama.LLAMA_DEBUG
    params = llama.init_params(config, jax.random.PRNGKey(0))
    b = ContinuousBatcher(params, config, GeneratorConfig(
        max_seq_len=64, batch_size=2, temperature=0.0,
        prompt_buckets=[16]))
    wait0 = _sample('skytpu_infer_queue_wait_seconds_count') or 0.0
    rids = [b.submit([5, 9, 2, 7], max_new_tokens=5),
            b.submit([11, 3], max_new_tokens=5)]
    b.run_until_idle()
    assert all(b.result(r) for r in rids)
    assert (_sample('skytpu_infer_queue_wait_seconds_count') or 0.0) \
        >= wait0 + 2
    # Idle after run_until_idle: the occupancy gauge reads 0.
    assert _sample('skytpu_infer_slot_occupancy_ratio') == 0.0


# --- trace-context propagation ---

def test_propagation_envs(monkeypatch, tmp_path):
    monkeypatch.delenv(trace_lib.ENV_VAR, raising=False)
    monkeypatch.delenv(timeline.ENV_VAR, raising=False)
    monkeypatch.delenv('SKYTPU_PROFILE_DIR', raising=False)
    assert trace_lib.propagation_envs() == {}
    monkeypatch.setenv(timeline.ENV_VAR, 'rel/trace.json')
    with trace_lib.trace_scope('abc123'):
        envs = trace_lib.propagation_envs()
    assert envs[trace_lib.ENV_VAR] == 'abc123'
    # Relative paths are absolutized: child processes run elsewhere.
    assert os.path.isabs(envs[timeline.ENV_VAR])


def test_trace_scope_nesting_and_fallback(monkeypatch):
    monkeypatch.setenv(trace_lib.ENV_VAR, 'from-env')
    assert trace_lib.get_trace_id() == 'from-env'
    with trace_lib.trace_scope('outer'):
        assert trace_lib.get_trace_id() == 'outer'
        with trace_lib.trace_scope(None):  # no-op scope
            assert trace_lib.get_trace_id() == 'outer'
    assert trace_lib.get_trace_id() == 'from-env'


def test_trace_id_survives_executor_dispatch(iso_state):  # noqa: F811
    """The executor rebinds the trace context on its worker side: a
    payload-stamped id (set by the server middleware) wins; without one
    the request id itself becomes the trace id."""
    from skypilot_tpu.server import executor
    seen = {}

    @executor.entrypoint('test.trace_probe')
    def _probe(payload):
        seen[payload['tag']] = trace_lib.get_trace_id()
        return {}

    try:
        rid = executor.schedule_request('test.trace_probe',
                                        {'tag': 'bare'})
        assert seen['bare'] == rid
        executor.schedule_request(
            'test.trace_probe',
            {'tag': 'stamped', trace_lib.PAYLOAD_KEY: 'stamp123'})
        assert seen['stamped'] == 'stamp123'
    finally:
        executor.REGISTRY.pop('test.trace_probe', None)


@pytest.mark.slow
def test_server_middleware_mints_and_echoes_trace_header(live_server):  # noqa: F811
    import requests
    resp = requests.get(live_server + '/api/health', timeout=10)
    minted = resp.headers.get(trace_lib.TRACE_HEADER)
    assert minted
    resp = requests.get(live_server + '/api/health', timeout=10,
                        headers={trace_lib.TRACE_HEADER: 'caller-id-1'})
    assert resp.headers.get(trace_lib.TRACE_HEADER) == 'caller-id-1'


# --- timeline spans ---

def test_timeline_spans_nest_and_merge_on_save(monkeypatch, tmp_path):
    path = str(tmp_path / 'trace.json')
    monkeypatch.setenv(timeline.ENV_VAR, path)
    with trace_lib.trace_scope('ttrace'):
        with timeline.Event('outer'):
            with timeline.Event('inner'):
                pass
    timeline.save()
    events = json.load(open(path))['traceEvents']
    by_name = {e['name']: e for e in events}
    assert 'parent' not in by_name['outer'].get('args', {})
    assert by_name['inner']['args']['parent'] == 'outer'
    assert by_name['outer']['args']['trace_id'] == 'ttrace'
    assert by_name['inner']['args']['trace_id'] == 'ttrace'
    # Second save MERGES (simulating another process appending) and a
    # drained buffer adds nothing — no duplicate spans.
    timeline.save()
    with timeline.Event('later'):
        pass
    timeline.save()
    names = [e['name'] for e in
             json.load(open(path))['traceEvents']]
    assert sorted(names) == ['inner', 'later', 'outer']


# --- JSONL step-telemetry ---

def test_steplog_roundtrip_and_limits(monkeypatch, tmp_path):
    path = str(tmp_path / 'steps.jsonl')
    monkeypatch.delenv(steplog.ENV_VAR, raising=False)
    assert not steplog.enabled()
    steplog.write({'kind': 'noop'})  # disabled: silently dropped
    monkeypatch.setenv(steplog.ENV_VAR, path)
    assert steplog.enabled()
    for i in range(5):
        steplog.write({'kind': 'step', 'i': i})
    with open(path, 'a', encoding='utf-8') as f:
        f.write('not json\n')
    # read() tails the last `limit` LINES and skips malformed ones, so
    # the garbage line occupies a slot but never surfaces.
    records = steplog.read(path, limit=3)
    assert [r['i'] for r in records] == [3, 4]
    assert all('ts' in r for r in records)
    assert [r['i'] for r in steplog.read(path)] == [0, 1, 2, 3, 4]
    assert steplog.read(str(tmp_path / 'missing.jsonl')) == []


# --- the acceptance e2e: one launch, one trace file, shared trace id ---

@pytest.mark.slow
def test_launch_single_trace_file_spans_processes(iso_state,  # noqa: F811
                                                  monkeypatch, tmp_path):
    """A single launch with SKYTPU_TIMELINE_FILE set yields ONE trace
    file whose spans come from more than one process (launcher + agent,
    at least) and share a common trace id."""
    from skypilot_tpu import execution
    from tests.test_launch_e2e import _make_task, _wait_job
    path = str(tmp_path / 'launch-trace.json')
    monkeypatch.setenv(timeline.ENV_VAR, path)
    monkeypatch.setenv(trace_lib.ENV_VAR, 'e2e-trace-1')
    job_id, handle = execution.launch(_make_task(run='echo traced'),
                                      cluster_name='ttrace',
                                      detach_run=True)
    from skypilot_tpu.utils.status_lib import JobStatus
    assert _wait_job(handle, job_id) == JobStatus.SUCCEEDED
    timeline.save()  # flush the launcher's stage spans

    def snapshot():
        try:
            return json.load(open(path)).get('traceEvents', [])
        except (OSError, ValueError):
            return []

    # The agent flushes its spans on submit; the gang driver at exit.
    deadline = time.time() + 30
    events = snapshot()
    while time.time() < deadline and \
            len({e['pid'] for e in events}) < 2:
        time.sleep(0.5)
        events = snapshot()
    names = {e['name'] for e in events}
    assert 'stage:PROVISION' in names and 'stage:EXEC' in names
    assert 'agent.submit' in names
    assert len({e['pid'] for e in events}) >= 2, names
    traced = {e['args']['trace_id'] for e in events
              if 'trace_id' in e.get('args', {})}
    assert traced == {'e2e-trace-1'}
