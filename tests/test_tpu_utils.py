import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.utils import tpu_utils


def test_parse_v5e_pod():
    spec = tpu_utils.parse_tpu_accelerator('tpu-v5e-256')
    assert spec.generation == 'v5e'
    assert spec.chips == 256
    assert spec.num_hosts == 64
    assert spec.chips_per_host == 4
    assert spec.gcp_accelerator_type == 'v5litepod-256'
    assert spec.is_pod


def test_parse_v5e_single_host():
    spec = tpu_utils.parse_tpu_accelerator('tpu-v5e-8')
    assert spec.num_hosts == 1
    assert spec.chips_per_host == 8
    assert not spec.is_pod


def test_core_counted_generations():
    v4 = tpu_utils.parse_tpu_accelerator('tpu-v4-8')
    assert v4.chips == 4 and v4.num_hosts == 1
    v3 = tpu_utils.parse_tpu_accelerator('v3-32')
    assert v3.chips == 16 and v3.num_hosts == 4
    v5p = tpu_utils.parse_tpu_accelerator('tpu-v5p-128')
    assert v5p.chips == 64 and v5p.num_hosts == 16


def test_aliases():
    a = tpu_utils.parse_tpu_accelerator('tpu-v5litepod-16')
    b = tpu_utils.parse_tpu_accelerator('v5e-16')
    assert a == b
    t = tpu_utils.parse_tpu_accelerator('trillium-8')
    assert t.generation == 'v6e'


def test_invalid_size_raises():
    with pytest.raises(exceptions.InvalidTaskError):
        tpu_utils.parse_tpu_accelerator('tpu-v5e-7')


def test_non_tpu_returns_none():
    assert tpu_utils.parse_tpu_accelerator('A100', validate=False) is None
    assert not tpu_utils.is_tpu_accelerator('H100-80GB')
    assert tpu_utils.is_tpu_accelerator('tpu-v6e-4')


def test_gke_topology_labels():
    from skypilot_tpu.utils.tpu_utils import parse_tpu_accelerator
    # 2D (v5e/v6e): ascending chip grid.
    assert parse_tpu_accelerator('tpu-v5e-8').topology == '2x4'
    assert parse_tpu_accelerator('tpu-v5e-16').topology == '4x4'
    assert parse_tpu_accelerator('tpu-v6e-32').topology == '4x8'
    assert parse_tpu_accelerator('tpu-v5e-1').topology == '1x1'
    # 3D (v4/v5p): ascending with 1s LAST, matching GKE labels (2x2x1).
    assert parse_tpu_accelerator('tpu-v4-8').topology == '2x2x1'
    assert parse_tpu_accelerator('tpu-v4-16').topology == '2x2x2'
    assert parse_tpu_accelerator('tpu-v4-32').topology == '2x2x4'
    assert parse_tpu_accelerator('tpu-v5e-8').gke_accelerator == \
        'tpu-v5-lite-podslice'
    assert parse_tpu_accelerator('tpu-v4-8').gke_accelerator == \
        'tpu-v4-podslice'
