"""Users/RBAC/tokens + workspaces (analog of the reference's
tests/unit_tests for sky/users and sky/workspaces)."""
import time

import pytest
import requests

from tests.test_api_server import live_server  # noqa: F401
from tests.test_launch_e2e import iso_state  # noqa: F401


# --- permission service / roles ---

def test_user_roles_and_default(iso_state):  # noqa: F811
    from skypilot_tpu.users import permission
    svc = permission.PermissionService()
    svc.add_user_if_not_exists('u1')
    assert svc.get_user_roles('u1') == ['admin']  # default role
    svc.update_role('u1', 'user')
    assert svc.get_user_roles('u1') == ['user']
    assert 'u1' in svc.get_users_for_role('user')
    with pytest.raises(ValueError):
        svc.update_role('u1', 'superuser')
    svc.delete_user('u1')
    assert svc.get_user_roles('u1') == []


def test_rbac_endpoint_blocklist(iso_state):  # noqa: F811
    from skypilot_tpu.users import permission
    svc = permission.PermissionService()
    svc.update_role('admin1', 'admin')
    svc.update_role('plain1', 'user')
    assert svc.check_endpoint_permission('admin1', '/users/create', 'POST')
    assert not svc.check_endpoint_permission('plain1', '/users/create',
                                             'POST')
    assert not svc.check_endpoint_permission('plain1', '/workspaces/delete',
                                             'POST')
    # Non-blocked endpoints stay open to plain users.
    assert svc.check_endpoint_permission('plain1', '/launch', 'POST')


def test_default_role_configurable(iso_state, monkeypatch):  # noqa: F811
    from skypilot_tpu import config
    from skypilot_tpu.users import permission
    # rbac config is server-side (not task-overridable): use the internal
    # context, as the server would after loading its config file.
    with config.override_context({'rbac': {'default_role': 'user'}}):
        svc = permission.PermissionService()
        svc.add_user_if_not_exists('u2')
        assert svc.get_user_roles('u2') == ['user']


def test_task_cannot_override_requesting_user(iso_state):  # noqa: F811
    import pytest as _pytest
    from skypilot_tpu import config
    from skypilot_tpu import exceptions
    with _pytest.raises(exceptions.InvalidSkyPilotConfigError):
        with config.override_config({'requesting_user': 'victim'}):
            pass


# --- tokens ---

def test_token_mint_verify_revoke(iso_state):  # noqa: F811
    from skypilot_tpu.users import token_service
    minted = token_service.create_token('ci-bot')
    user_id = token_service.verify_token(minted['token'])
    assert user_id == minted['user_id']
    # Tampered token fails.
    assert token_service.verify_token(minted['token'][:-1] + 'x') is None
    assert token_service.verify_token('skytpu_sa_bogus.deadbeef') is None
    listed = token_service.list_tokens()
    assert any(t['token_id'] == minted['token_id'] and t['last_used_at']
               for t in listed)
    token_service.revoke_token(minted['token_id'])
    assert token_service.verify_token(minted['token']) is None


def test_token_expiry(iso_state):  # noqa: F811
    from skypilot_tpu.users import state as users_state
    from skypilot_tpu.users import token_service
    minted = token_service.create_token('short', expires_in_days=1)
    # Force-expire in the DB.
    with users_state._conn() as conn:  # pylint: disable=protected-access
        conn.execute('UPDATE tokens SET expires_at = ? WHERE token_id = ?',
                     (time.time() - 1, minted['token_id']))
    assert token_service.verify_token(minted['token']) is None


# --- workspaces ---

def test_workspace_crud(iso_state):  # noqa: F811
    from skypilot_tpu import exceptions
    from skypilot_tpu.workspaces import core
    assert 'default' in core.get_workspaces()
    core.create_workspace('team-a', {})
    assert 'team-a' in core.get_workspaces()
    with pytest.raises(exceptions.SkyTpuError):
        core.create_workspace('team-a', {})     # duplicate
    with pytest.raises(exceptions.SkyTpuError):
        core.create_workspace('bad', {'nope': 1})  # unknown key
    with pytest.raises(exceptions.SkyTpuError):
        core.delete_workspace('default')
    core.delete_workspace('team-a')
    assert 'team-a' not in core.get_workspaces()


def test_private_workspace_visibility(iso_state):  # noqa: F811
    from skypilot_tpu import exceptions
    from skypilot_tpu.users import permission
    from skypilot_tpu.workspaces import core
    svc = permission.permission_service
    svc.update_role('alice', 'user')
    svc.update_role('bob', 'user')
    svc.update_role('root', 'admin')
    with pytest.raises(exceptions.SkyTpuError):
        core.create_workspace('secret', {'private': True})  # no users
    core.create_workspace('secret',
                          {'private': True, 'allowed_users': ['alice']})
    assert 'secret' in core.workspaces_for_user('alice')
    assert 'secret' not in core.workspaces_for_user('bob')
    assert 'secret' in core.workspaces_for_user('root')  # admin sees all
    # Flip to public: everyone sees it.
    core.update_workspace('secret', {})
    assert 'secret' in core.workspaces_for_user('bob')


def test_workspace_delete_blocked_by_active_cluster(iso_state):  # noqa: F811
    from skypilot_tpu import exceptions
    from skypilot_tpu import state
    from skypilot_tpu.execution import launch
    from skypilot_tpu.task import Task
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.workspaces import core
    from skypilot_tpu import config

    core.create_workspace('busy', {})
    task = Task(name='t', run='echo hi')
    task.set_resources(Resources(cloud='local'))
    with config.override_config({'active_workspace': 'busy'}):
        launch(task, cluster_name='ws-c1')
    record = state.get_cluster('ws-c1')
    assert record['workspace'] == 'busy'
    with pytest.raises(exceptions.SkyTpuError):
        core.delete_workspace('busy')
    from skypilot_tpu.backends import TpuBackend
    TpuBackend().teardown(record['handle'])
    state.remove_cluster('ws-c1')
    core.delete_workspace('busy')


# --- REST + auth middleware ---

def test_users_rest_roundtrip(live_server):  # noqa: F811
    resp = requests.post(live_server + '/users/create',
                         json={'name': 'carol', 'password': 'pw',
                               'role': 'user'}, timeout=10)
    assert resp.status_code == 200, resp.text
    uid = resp.json()['id']
    users = requests.get(live_server + '/users/list', timeout=10).json()
    assert any(u['id'] == uid and u['role'] == 'user'
               for u in users['users'])
    # Duplicate name rejected.
    assert requests.post(live_server + '/users/create',
                         json={'name': 'carol'},
                         timeout=10).status_code == 409
    resp = requests.post(live_server + '/users/update',
                         json={'id': uid, 'role': 'admin'}, timeout=10)
    assert resp.status_code == 200
    resp = requests.post(live_server + '/users/delete', json={'id': uid},
                         timeout=10)
    assert resp.status_code == 200


def test_workspaces_rest_roundtrip(live_server):  # noqa: F811
    resp = requests.post(live_server + '/workspaces/create',
                         json={'name': 'ws-rest', 'config': {}}, timeout=10)
    assert resp.status_code == 200, resp.text
    listed = requests.get(live_server + '/workspaces', timeout=10).json()
    assert 'ws-rest' in listed and 'default' in listed
    resp = requests.post(live_server + '/workspaces/delete',
                         json={'name': 'ws-rest'}, timeout=10)
    assert resp.status_code == 200


def test_auth_enforced_basic_and_token(live_server):  # noqa: F811
    from skypilot_tpu.users import token_service
    # Create a password user + a service-account token while auth is off.
    requests.post(live_server + '/users/create',
                  json={'name': 'dave', 'password': 's3cret',
                        'role': 'user'}, timeout=10)
    minted = token_service.create_token('ci')
    # The server runs in another thread, so thread-local override_config
    # can't reach it — write the user config file and reload (process-wide).
    import os
    from skypilot_tpu import config
    cfg_path = os.environ['SKYTPU_CONFIG']
    with open(cfg_path, 'w', encoding='utf-8') as f:
        f.write('api_server:\n  auth_enabled: true\n')
    config.reload_config()
    try:
        # Bad basic credentials -> 401.
        resp = requests.get(live_server + '/users/list',
                            auth=('dave', 'wrong'), timeout=10)
        assert resp.status_code == 401
        # Good basic credentials, but role 'user' blocked from POST
        # /users/create -> 403.
        resp = requests.post(live_server + '/users/create',
                             json={'name': 'eve'}, auth=('dave', 's3cret'),
                             timeout=10)
        assert resp.status_code == 403
        # user can still GET /users/list.
        resp = requests.get(live_server + '/users/list',
                            auth=('dave', 's3cret'), timeout=10)
        assert resp.status_code == 200
        # Bearer token works (sa users get the default role: admin).
        resp = requests.get(
            live_server + '/users/list',
            headers={'Authorization': f'Bearer {minted["token"]}'},
            timeout=10)
        assert resp.status_code == 200
        # Bogus bearer -> 401.
        resp = requests.get(
            live_server + '/users/list',
            headers={'Authorization': 'Bearer skytpu_sa_x.y'}, timeout=10)
        assert resp.status_code == 401
        # No credentials at all -> 401 (credentials are mandatory under
        # enforcement; the local-user fallback must not apply).
        resp = requests.get(live_server + '/users/list', timeout=10)
        assert resp.status_code == 401
        # The identity header is NOT trusted outside proxy mode.
        resp = requests.get(live_server + '/users/list',
                            headers={'X-SkyTPU-User': 'anyone'}, timeout=10)
        assert resp.status_code == 401
        # Health stays open for probes.
        resp = requests.get(live_server + '/api/health', timeout=10)
        assert resp.status_code == 200
        # A plain user cannot mint a token for another (admin) user.
        resp = requests.post(
            live_server + '/users/token/create',
            json={'name': 'evil', 'user_id': 'user-someadmin'},
            auth=('dave', 's3cret'), timeout=10)
        assert resp.status_code == 403
        # A plain user CAN mint their own SA token, but the SA inherits
        # role 'user' — no default-admin escalation.
        resp = requests.post(live_server + '/users/token/create',
                             json={'name': 'dave-ci'},
                             auth=('dave', 's3cret'), timeout=10)
        assert resp.status_code == 200
        sa = resp.json()
        from skypilot_tpu.users import permission as perm
        assert perm.permission_service.get_user_roles(
            sa['user_id']) == ['user']
        # dave sees only his own tokens; cannot revoke someone else's.
        resp = requests.get(live_server + '/users/token/list',
                            auth=('dave', 's3cret'), timeout=10)
        listed = resp.json()['tokens']
        assert all(t['user_id'] == sa['user_id'] for t in listed)
        resp = requests.post(live_server + '/users/token/revoke',
                             json={'token_id': minted['token_id']},
                             auth=('dave', 's3cret'), timeout=10)
        assert resp.status_code == 403
        # ...but can revoke his own.
        resp = requests.post(live_server + '/users/token/revoke',
                             json={'token_id': sa['token_id']},
                             auth=('dave', 's3cret'), timeout=10)
        assert resp.status_code == 200
    finally:
        os.remove(cfg_path)
        config.reload_config()


def test_proxy_mode_trusts_identity_header(live_server):  # noqa: F811
    import os
    from skypilot_tpu import config
    cfg_path = os.environ['SKYTPU_CONFIG']
    with open(cfg_path, 'w', encoding='utf-8') as f:
        f.write('api_server:\n  auth_enabled: true\n  auth_mode: proxy\n')
    config.reload_config()
    try:
        resp = requests.get(live_server + '/users/list',
                            headers={'X-SkyTPU-User': 'proxy-user'},
                            timeout=10)
        assert resp.status_code == 200
    finally:
        os.remove(cfg_path)
        config.reload_config()


def test_token_create_does_not_rename_user(iso_state):  # noqa: F811
    from skypilot_tpu.users import state as users_state
    from skypilot_tpu.users import token_service
    from skypilot_tpu.users.models import User
    users_state.add_or_update_user(User.new('user-carol', name='carol'))
    token_service.create_token('ci-token', user_id='user-carol')
    assert users_state.get_user_by_name('carol') is not None


def test_password_hashing_pbkdf2(iso_state):  # noqa: F811
    from skypilot_tpu.users import state as users_state
    h1 = users_state.hash_password('pw')
    h2 = users_state.hash_password('pw')
    assert h1 != h2                      # per-user salt
    assert h1.startswith('pbkdf2$')
    assert users_state.verify_password('pw', h1)
    assert not users_state.verify_password('wrong', h1)
    assert not users_state.verify_password('pw', 'garbage')
