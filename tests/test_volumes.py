"""Volumes: lifecycle on the local provisioner + task mount wiring
(reference analog: sky/volumes tests + provision hook tests)."""
import os

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.volumes import core as vol_core
from tests.test_launch_e2e import iso_state  # noqa: F401  (fixture reuse)


def test_volume_lifecycle(iso_state):  # noqa: F811
    volume = vol_core.Volume(name='v1', cloud='local', size_gb=1)
    record = vol_core.apply(volume)
    assert record['status'] == vol_core.VolumeStatus.READY
    # Idempotent re-apply.
    assert vol_core.apply(volume)['created_at'] == record['created_at']
    assert [r['name'] for r in vol_core.ls()] == ['v1']
    vol_core.delete('v1')
    assert vol_core.ls() == []
    with pytest.raises(exceptions.StorageError):
        vol_core.delete('v1')


def test_volume_yaml_parsing():
    volume = vol_core.Volume.from_yaml_config(
        {'name': 'ckpt', 'size': '200Gi', 'type': 'pd-balanced',
         'zone': 'us-central1-a'})
    assert volume.size_gb == 200 and volume.type == 'pd-balanced'
    with pytest.raises(exceptions.StorageSpecError):
        vol_core.Volume.from_yaml_config({'size': '10Gi'})


def test_task_volume_mounted_end_to_end(iso_state):  # noqa: F811
    from skypilot_tpu import execution
    from skypilot_tpu.provision.local import volume as lvol
    vol_core.apply(vol_core.Volume(name='data-vol', cloud='local'))
    # Seed a file in the volume; the task should see it at the mount path.
    with open(os.path.join(lvol.volume_dir('data-vol'), 'hello.txt'),
              'w', encoding='utf-8') as f:
        f.write('from-volume')
    mount_path = os.path.expanduser('~/.skypilot_tpu/mnt/data')
    task = task_lib.Task.from_yaml_config({
        'name': 'vol-task',
        'run': f'cat {mount_path}/hello.txt',
        'resources': {'cloud': 'local'},
        'volumes': {mount_path: 'data-vol'},
    })
    assert task.to_yaml_config()['volumes'] == {mount_path: 'data-vol'}
    job_id, handle = execution.launch(task, cluster_name='vol-c1')
    from skypilot_tpu.backends import TpuBackend
    status = TpuBackend().wait_job(handle, job_id, timeout=60)
    assert status.value == 'SUCCEEDED'
    record = vol_core.get('data-vol')
    assert record['status'] == vol_core.VolumeStatus.IN_USE
    assert record['last_attached_to'] == 'vol-c1'
    TpuBackend().teardown(handle, terminate=True)


def test_missing_volume_raises(iso_state):  # noqa: F811
    from skypilot_tpu import execution
    task = task_lib.Task.from_yaml_config({
        'name': 'vol-task2', 'run': 'true',
        'resources': {'cloud': 'local'},
        'volumes': {'/tmp/nope': 'ghost-vol'},
    })
    with pytest.raises(exceptions.StorageError):
        execution.launch(task, cluster_name='vol-c2')
    from skypilot_tpu import core as core_lib
    core_lib.down('vol-c2')
